"""Hyperparameter search with the Arbiter-role API.

Random search over learning rate (log-uniform — the right prior) and
hidden width; the runner trains/scores each candidate, appends crash-safe
jsonl progress, and serializes the best model.

Run:  python examples/hpo_search.py          (EXAMPLE_QUICK=1 to smoke)
"""

import os
import tempfile

import numpy as np

from deeplearning4j_tpu.arbiter import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    EvaluationScoreFunction,
    OptimizationRunner,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")


def data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    cls = rng.integers(0, 2, n)
    x = (rng.normal(0, 0.6, (n, 8)) + cls[:, None]).astype(np.float32)
    return DataSet(x, np.eye(2, dtype=np.float32)[cls])


def main():
    train, val = data(seed=0), data(seed=1)

    def model_factory(cand: dict) -> SequentialModel:
        conf = (
            NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(cand["lr"]))
            .list()
            .layer(Dense(n_out=int(cand["hidden"]), activation=Activation.RELU))
            .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build()
        )
        return SequentialModel(conf).init()

    out_dir = tempfile.mkdtemp()
    runner = OptimizationRunner(
        RandomSearchGenerator(
            {
                "lr": ContinuousParameterSpace(1e-4, 1e-1, log=True),
                "hidden": DiscreteParameterSpace([8, 32, 64]),
            },
            seed=3,
        ),
        model_factory,
        EvaluationScoreFunction(val, metric="accuracy"),
        fitter=lambda model: model.fit(
            train, epochs=3 if QUICK else 15, batch_size=64
        ),
        max_candidates=3 if QUICK else 12,
        results_path=os.path.join(out_dir, "hpo.jsonl"),
        save_best_dir=out_dir,
    ).execute()

    best = runner.best()
    print("best candidate:", best.candidate, "accuracy:", best.score)
    print("results:", os.path.join(out_dir, "hpo.jsonl"))
    return best.score


if __name__ == "__main__":
    main()
