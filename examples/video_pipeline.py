"""Video classification end to end: MJPEG-AVI clips -> VideoRecordReader
-> per-clip features -> a classifier trained with steps_per_execution.

Demonstrates three round-3 capabilities together:
  * `datavec.video` — codec-free MJPEG-AVI write + read (frames decode
    through PIL; the RIFF container is parsed with the stdlib),
  * `LocalTransformExecutor` — the partition-parallel (Spark-executor
    role) tier for tabular side-features,
  * `fit(..., steps_per_execution=k)` — k optimizer steps per compiled
    XLA program, the dispatch-latency killer for small models.

Run:  python examples/video_pipeline.py       (EXAMPLE_QUICK=1 to smoke)
"""

import os
import tempfile
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data import DataSet, NumpyDataSetIterator
from deeplearning4j_tpu.datavec import (
    LocalTransformExecutor,
    Schema,
    TransformProcess,
)
from deeplearning4j_tpu.datavec.video import VideoRecordReader, write_mjpeg_avi
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")


def make_corpus(root: Path, clips_per_class: int):
    """Two 'activities' with distinct temporal dynamics: flicker (frame
    brightness alternates) vs steady.  The MEAN frame can't separate them;
    the frame-to-frame delta can — a genuinely temporal signal."""
    rng = np.random.default_rng(0)
    T, H, W = 6, 24, 32
    for label in ("flicker", "steady"):
        d = root / label
        d.mkdir(parents=True)
        for i in range(clips_per_class):
            base = rng.uniform(80, 170)
            frames = np.full((T, H, W, 3), base, np.float32)
            if label == "flicker":
                frames[1::2] += 60.0
            frames += rng.normal(0, 6, frames.shape)
            write_mjpeg_avi(
                d / f"{i}.avi",
                np.clip(frames, 0, 255).astype(np.uint8),
                fps=10,
            )


def clip_features(frames: np.ndarray) -> list:
    """Per-clip temporal features: mean |frame delta| and overall mean."""
    deltas = np.abs(np.diff(frames.mean(axis=(1, 2, 3))))
    return [float(deltas.mean()), float(frames.mean())]


def main() -> float:
    clips = 8 if QUICK else 32
    root = Path(tempfile.mkdtemp(prefix="videos_"))
    make_corpus(root, clips)

    reader = VideoRecordReader(16, 16, 3, shuffle_seed=7).initialize(root)
    print(f"classes: {reader.labels}, clips: {reader.num_videos()}")

    rows, labels = [], []
    for frames, label in reader:
        rows.append(clip_features(frames))
        labels.append(label)

    # normalize the tabular features through a TransformProcess (the
    # partition-parallel executor kicks in on big corpora; this small one
    # stays serial automatically)
    schema = Schema.builder().add_double("delta").add_double("bright").build()
    tp = (
        TransformProcess.builder(schema)
        .normalize_min_max("delta", 0.0, 80.0)
        .normalize_min_max("bright", 0.0, 255.0)
        .build()
    )
    rows = LocalTransformExecutor.execute(tp, rows, num_workers=2)

    x = np.asarray(rows, np.float32)
    y = np.eye(2, dtype=np.float32)[np.asarray(labels)]

    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .updater(Adam(5e-2))
        .list()
        .layer(Dense(n_out=16, activation=Activation.TANH))
        .layer(OutputLayer(n_out=2, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(2))
        .build()
    )
    model = SequentialModel(conf).init()
    model.fit(
        NumpyDataSetIterator(x, y, batch_size=8, seed=1),
        epochs=10 if QUICK else 40,
        steps_per_execution=4,        # 4 optimizer steps per XLA dispatch
    )
    acc = model.evaluate(DataSet(x, y)).accuracy()
    print(f"train accuracy: {acc:.3f}")
    assert acc > 0.9, f"video classifier failed to learn ({acc})"
    return acc


if __name__ == "__main__":
    main()
