"""Import a frozen TF graph and fine-tune it — THROUGH its while loop.

The reference's transfer-learning entry path (TFGraphMapper.importGraph ->
promote weights -> attach loss -> fit; SURVEY.md §3.3, BASELINE config 4),
exercised end to end with zero tensorflow dependency:

1. a "pretrained" frozen GraphDef is synthesized with the self-contained
   wire codec (`modelimport._tf.synthetic.FrozenGraphWriter`) — in real
   use this is the `.pb` your training stack exported.  The graph runs a
   recurrent refinement LOOP in TF's V1 frame representation
   (Enter/Merge/Switch/NextIteration/Exit) — the hard case;
2. `import_graph(..., trainable=True)` reconstructs the loop, PROVES its
   trip count static, lowers it to `lax.scan` (reverse-mode
   differentiable) and promotes the float weights — including the one
   captured INSIDE the loop body — to trainable variables;
3. a task head + softmax-cross-entropy loss is attached and the whole
   thing fine-tunes as ONE compiled XLA step; the in-loop weight
   verifiably moves.

Run:  python examples/finetune_imported.py   (EXAMPLE_QUICK=1 for tests)
"""

import os

import numpy as np

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")

B, D, K, TRIPS = 16, 8, 3, 4


def build_frozen_graph(seed: int = 0) -> bytes:
    """Synthesize the 'pretrained' frozen graph: x -> [loop: h = tanh(h @
    W_loop) x4] -> logits = h @ W_head, with the loop in V1 frame form."""
    from deeplearning4j_tpu.modelimport._tf.synthetic import FrozenGraphWriter

    rng = np.random.default_rng(seed)
    w = FrozenGraphWriter()
    INT = {"T": 3}          # DT_INT32
    FLT = {"T": 1}          # DT_FLOAT

    x = w.placeholder("x", np.float32, [None, D])
    w_loop = w.const("W_loop", (rng.normal(size=(D, D)) * 0.4).astype(np.float32))
    w_head = w.const("W_head", (rng.normal(size=(D, K)) * 0.4).astype(np.float32))
    i0 = w.const("i0", np.asarray(0, np.int32))
    n = w.const("n_trips", np.asarray(TRIPS, np.int32))
    one = w.const("one", np.asarray(1, np.int32))

    # V1 while frame "rec": what tf.compat.v1.while_loop(lower_control_flow
    # =True) would freeze to.  Loop vars: (i, h); W_loop enters as a
    # loop-invariant capture (is_constant).
    ei = w.node("Enter", "rec/enter_i", [i0], types=INT,
                frame_name="rec", is_constant=False)
    eh = w.node("Enter", "rec/enter_h", [x], types=FLT,
                frame_name="rec", is_constant=False)
    ew = w.node("Enter", "rec/enter_W", [w_loop], types=FLT,
                frame_name="rec", is_constant=True)
    en = w.node("Enter", "rec/enter_n", [n], types=INT,
                frame_name="rec", is_constant=True)
    e1 = w.node("Enter", "rec/enter_one", [one], types=INT,
                frame_name="rec", is_constant=True)
    mi = w.node("Merge", "rec/merge_i", [ei, "rec/next_i"], types=INT, N=2)
    mh = w.node("Merge", "rec/merge_h", [eh, "rec/next_h"], types=FLT, N=2)
    less = w.node("Less", "rec/less", [mi, en], types=INT)
    lc = w.node("LoopCond", "rec/cond", [less])
    si = w.node("Switch", "rec/switch_i", [mi, lc], types=INT)
    sh = w.node("Switch", "rec/switch_h", [mh, lc], types=FLT)
    inc = w.node("AddV2", "rec/inc", [f"{si}:1", e1], types=INT)
    mm = w.node("MatMul", "rec/matmul", [f"{sh}:1", ew], types=FLT,
                transpose_a=False, transpose_b=False)
    th = w.node("Tanh", "rec/tanh", [mm], types=FLT)
    w.node("NextIteration", "rec/next_i", [inc], types=INT)
    w.node("NextIteration", "rec/next_h", [th], types=FLT)
    w.node("Exit", "rec/exit_h", [sh], types=FLT)
    w.matmul("rec/exit_h", w_head, name="head")
    w.node("Identity", "logits", ["head"], types=FLT)
    return w.serialize()


def main() -> float:
    from deeplearning4j_tpu.autodiff.samediff import TrainingConfig
    from deeplearning4j_tpu.modelimport.tensorflow import import_graph
    from deeplearning4j_tpu.nn.updaters import Adam

    sd = import_graph(build_frozen_graph(), trainable=True)

    # the loop imported as a differentiable scan with a PROVEN trip count
    (wnode,) = [op for op in sd._ops if op.op == "_while"]
    assert wnode.attrs["max_trip"] == TRIPS and wnode.attrs["exact_trip"]
    assert "W_loop" in sd._trainable        # in-loop weight promoted
    print(f"imported: loop -> lax.scan (trip={wnode.attrs['max_trip']}), "
          f"trainables: {sorted(sd._trainable)}")

    # synthetic class-conditional task on the loop's output
    rng = np.random.default_rng(1)
    y_idx = rng.integers(0, K, B)
    x = (rng.normal(0, 1, (B, D)) + 1.2 * y_idx[:, None]).astype(np.float32)
    y = np.eye(K, dtype=np.float32)[y_idx]

    labels = sd.placeholder("labels")
    sd.set_loss(sd.loss.softmax_cross_entropy(sd["logits"], labels,
                                              name="loss"))
    sd.set_training_config(TrainingConfig(updater=Adam(5e-2)))

    w0 = np.asarray(sd.get_value("W_loop")).copy()
    steps = 20 if QUICK else 120
    losses = [sd.fit_batch({"x": x, "labels": y}) for _ in range(steps)]
    moved = float(np.abs(np.asarray(sd.get_value("W_loop")) - w0).max())
    print(f"fine-tune: loss {losses[0]:.3f} -> {losses[-1]:.3f}; "
          f"in-loop weight moved {moved:.4f} (gradient crossed the loop)")
    assert losses[-1] < losses[0] and moved > 1e-4

    acc = float((np.asarray(sd.output({"x": x}, "logits")).argmax(1)
                 == y_idx).mean())
    print(f"train accuracy after fine-tune: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
