"""Multi-chip parallelism on a virtual device mesh — no hardware needed.

Shows the one-call `distribute()` API composing data + tensor parallelism,
and int8-compressed gradients, over an 8-device mesh.  On a real slice
the same code runs unchanged; here XLA_FLAGS fakes 8 CPU devices (set
BEFORE jax initializes, which is why it happens at the top).

Run:  python examples/multichip_parallel.py
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax          # noqa: E402

# Pick the platform BEFORE anything initializes a backend — calling
# jax.default_backend()/jax.devices() first would lock the platform in and
# make this update a silent no-op.  The 8-device mesh exists only on the
# virtual CPU platform, so the example defaults to CPU; set
# EXAMPLE_FORCE_TPU=1 on a real >=8-chip slice.
if os.environ.get("EXAMPLE_FORCE_TPU", "") in ("", "0"):
    jax.config.update("jax_platforms", "cpu")

if len(jax.devices()) < 8:
    raise SystemExit(
        f"need 8 devices for the mesh, have {len(jax.devices())} "
        f"{jax.default_backend()} device(s); unset EXAMPLE_FORCE_TPU to "
        "run on the virtual CPU mesh"
    )

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data import DataSet                      # noqa: E402
from deeplearning4j_tpu.models import SequentialModel            # noqa: E402
from deeplearning4j_tpu.nn import Adam                           # noqa: E402
from deeplearning4j_tpu.nn.activations import Activation         # noqa: E402
from deeplearning4j_tpu.nn.conf import (                         # noqa: E402
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss                    # noqa: E402
from deeplearning4j_tpu.parallel import ParallelConfig, distribute  # noqa: E402

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")


def make_model():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .updater(Adam(5e-3))
        .list()
        .layer(Dense(n_out=256, activation=Activation.RELU))
        .layer(Dense(n_out=256, activation=Activation.RELU))
        .layer(OutputLayer(n_out=4, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(16))
        .build()
    )
    return SequentialModel(conf).init()


def data(n=2048):
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 4, n)
    x = (rng.normal(0, 0.5, (n, 16)) + cls[:, None] * 0.7).astype(np.float32)
    return DataSet(x, np.eye(4, dtype=np.float32)[cls])


def main():
    ds = data()
    epochs = 2 if QUICK else 10

    # data parallel x tensor parallel over one mesh
    m = make_model()
    distribute(m, ParallelConfig(data=4, model=2))
    m.fit(ds, epochs=epochs, batch_size=256)
    print(f"DP4 x TP2 accuracy: {m.evaluate(ds).accuracy():.4f}")

    # pure DP with int8 error-feedback gradient compression (the DCN play)
    m2 = make_model()
    distribute(m2, ParallelConfig(data=8, grad_compression="int8"))
    m2.fit(ds, epochs=epochs, batch_size=256)
    print(f"DP8 int8-compressed accuracy: {m2.evaluate(ds).accuracy():.4f}")
    return m2.evaluate(ds).accuracy()


if __name__ == "__main__":
    main()
