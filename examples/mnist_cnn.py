"""LeNet-style CNN on MNIST — the canonical first example (the
reference's LenetMnistExample role).

Run:  python examples/mnist_cnn.py
Set EXAMPLE_QUICK=1 for a seconds-long smoke run (used by the tests).
"""

import os

from deeplearning4j_tpu.data.builtin import MnistDataSetIterator
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Conv2D,
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.train import PerformanceListener, ScoreIterationListener

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")


def build_model() -> SequentialModel:
    conf = (
        NeuralNetConfiguration.builder()
        .seed(123)
        .updater(Adam(1e-3))
        .activation(Activation.RELU)
        .list()
        .layer(Conv2D(n_out=20, kernel=(5, 5)))
        .layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
        .layer(Conv2D(n_out=50, kernel=(5, 5)))
        .layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
        .layer(Dense(n_out=500))
        .layer(OutputLayer(n_out=10, loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    return SequentialModel(conf).init()


def main() -> float:
    n_train = 2000 if QUICK else 60000
    epochs = 1 if QUICK else 3
    train = MnistDataSetIterator(batch_size=128, train=True, num_examples=n_train)
    test = MnistDataSetIterator(batch_size=512, train=False,
                                num_examples=1000 if QUICK else 10000)
    model = build_model()
    model.set_listeners(ScoreIterationListener(20), PerformanceListener(20))
    model.fit(train, epochs=epochs)
    acc = model.evaluate(test).accuracy()
    print(f"test accuracy: {acc:.4f}")
    model.save("/tmp/mnist_cnn.zip")
    print("saved to /tmp/mnist_cnn.zip")
    return acc


if __name__ == "__main__":
    main()
