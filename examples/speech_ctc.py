"""Speech-style sequence recognition with CTC — tone "digits" to label
strings with no frame alignment.

Role: the reference's speech stacks train through its `ctc_loss`
declarable op (SURVEY.md §2.1 op families); this example drives the
TPU-native equivalent end to end: WAV corpus on disk → stdlib decode +
numpy spectrogram (DataVec audio tier) → a SameDiff acoustic model whose
WHOLE step (features → per-frame logits → CTC log-alpha recursion →
Adam update) compiles into ONE XLA program — the lax.scan inside
`ops_registry._ctc_loss` rides the same jit as the network.  Decoding
uses the registry's `ctc_greedy_decode` (+lengths), also jit-compiled.

Each clip is a random 3-digit sequence of pure tones separated by
silence; labels are the digit ids with NO timing information — CTC
learns the alignment itself.

Run:  python examples/speech_ctc.py       (EXAMPLE_QUICK=1 to smoke)
"""

import os
import tempfile
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.autodiff.ops_registry import OPS
from deeplearning4j_tpu.autodiff.samediff import SameDiff, TrainingConfig
from deeplearning4j_tpu.datavec import read_wav, spectrogram, write_wav
from deeplearning4j_tpu.nn.updaters import Adam

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")
RATE = 8000
N_DIGITS = 4                     # vocabulary: digits 0..3
BLANK = N_DIGITS                 # CTC blank = last class
SEQ_LEN = 3                      # spoken digits per clip
TONE_S, GAP_S = 0.08, 0.04       # per-digit tone / silence durations


def digit_freq(d: int) -> float:
    return 300.0 * (1.6 ** d)


def make_corpus(root: Path, n_clips: int, rng) -> list[tuple[Path, list[int]]]:
    items = []
    for i in range(n_clips):
        digits = rng.integers(0, N_DIGITS, SEQ_LEN).tolist()
        wave = [np.zeros(int(GAP_S * RATE), np.float32)]
        for d in digits:
            t = np.arange(int(TONE_S * RATE)) / RATE
            tone = 0.5 * np.sin(2 * np.pi * digit_freq(d) * t)
            wave += [tone.astype(np.float32),
                     np.zeros(int(GAP_S * RATE), np.float32)]
        path = root / f"clip{i:03d}.wav"
        write_wav(path, np.concatenate(wave), RATE)
        items.append((path, digits))
    return items


def featurize(items):
    feats, labels = [], []
    for path, digits in items:
        samples, _ = read_wav(path)
        # spectrogram() already returns LOG magnitude by default
        spec = spectrogram(samples, frame_length=256, frame_step=128)
        feats.append(spec.astype(np.float32))
        labels.append(digits)
    x = np.stack(feats)                       # (B, T_frames, F)
    # per-bin standardization: log-magnitude bins differ wildly in mean
    # (silence floor vs tone bins); global stats leave the tone structure
    # tiny relative to the floor offset
    mu = x.mean(axis=(0, 1), keepdims=True)
    sd = x.std(axis=(0, 1), keepdims=True)
    x = (x - mu) / (sd + 1e-6)
    return x, np.asarray(labels, np.int32)


def build_model(n_frames: int, n_feat: int, hidden: int, rng) -> SameDiff:
    sd = SameDiff()
    x = sd.placeholder("x")                   # (B, T, F)
    w1 = sd.var("w1", rng.normal(0, n_feat ** -0.5, (n_feat, hidden)))
    b1 = sd.var("b1", np.zeros(hidden, np.float32))
    w2 = sd.var("w2", rng.normal(0, hidden ** -0.5, (hidden, N_DIGITS + 1)))
    b2 = sd.var("b2", np.zeros(N_DIGITS + 1, np.float32))
    h = sd.apply("tanh", sd.apply("add", sd.apply("matmul", x, w1), b1))
    logits = sd.apply("add", sd.apply("matmul", h, w2), b2, name="logits")
    labels = sd.placeholder("labels")
    sd.set_loss(sd.apply("ctc_loss", logits, labels, blank=BLANK,
                         name="loss"))
    sd.set_training_config(TrainingConfig(updater=Adam(3e-3)))
    return sd


def main() -> float:
    rng = np.random.default_rng(0)
    root = Path(tempfile.mkdtemp())
    n_clips = 24 if QUICK else 96
    items = make_corpus(root, n_clips, rng)
    x, labels = featurize(items)
    print(f"{len(x)} clips, frames={x.shape[1]}, features={x.shape[2]}, "
          f"labels {SEQ_LEN}/clip over {N_DIGITS} digits + blank")

    sd = build_model(x.shape[1], x.shape[2], 48 if QUICK else 96, rng)
    epochs = 400 if QUICK else 250
    batch = 24
    losses = []
    for _ in range(epochs):
        order = rng.permutation(len(x))
        for i in range(0, len(x), batch):
            sel = order[i:i + batch]
            losses.append(sd.fit_batch({"x": x[sel], "labels": labels[sel]}))
    print(f"CTC loss {losses[0]:.3f} -> {losses[-1]:.3f}")

    # decode (registry ops, jit-compiled): greedy best-path AND prefix
    # beam search -> exact-sequence accuracy
    import jax

    logits = sd.output({"x": x}, "logits")
    decode = jax.jit(lambda lg: (
        OPS["ctc_greedy_decode"](lg, blank=BLANK),
        OPS["ctc_greedy_decode_lengths"](lg, blank=BLANK),
    ))
    dec, lens = (np.asarray(v) for v in decode(logits))
    greedy_acc = np.mean([
        lens[i] == SEQ_LEN and (dec[i][:SEQ_LEN] == labels[i]).all()
        for i in range(len(x))
    ])
    from deeplearning4j_tpu.autodiff.ops_registry import ctc_beam_search

    bpre, blen, _ = (np.asarray(v) for v in jax.jit(
        lambda lg: ctc_beam_search(lg, beam_width=8, blank=BLANK)
    )(logits))
    beam_acc = np.mean([
        blen[i, 0] == SEQ_LEN
        and (bpre[i, 0][:SEQ_LEN] == labels[i]).all()
        for i in range(len(x))
    ])
    print(f"exact-sequence accuracy: greedy {greedy_acc:.3f}, "
          f"beam(8) {beam_acc:.3f}")
    return max(greedy_acc, beam_acc)


if __name__ == "__main__":
    raise SystemExit(0 if main() > 0.9 else 1)
