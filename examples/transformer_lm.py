"""Character-level transformer LM: chunked-vocab loss + KV-cache sampling.

Trains a small causal transformer on synthetic "abab..." grammar text,
then generates continuations with the KV-cache decoder.

Run:  python examples/transformer_lm.py        (EXAMPLE_QUICK=1 to smoke)
"""

import os

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.ops.generation import generate
from deeplearning4j_tpu.zoo.transformer import TransformerEncoder

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")

VOCAB = 16
ALPHABET = "abcdefghijklmnop"


def corpus(n_seqs=512, seq_len=32, seed=0):
    """Deterministic cyclic grammar: token (i+1) follows token i."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, VOCAB, n_seqs)
    ids = (starts[:, None] + np.arange(seq_len)[None, :]) % VOCAB
    return ids


def main() -> float:
    steps = 30 if QUICK else 300
    ids = corpus(128 if QUICK else 512)
    x = ids.astype(np.float32)
    y = np.roll(ids, -1, axis=1).astype(np.float32)   # int next-token ids

    model = TransformerEncoder(
        vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=2, causal=True,
        chunked_vocab_loss=True, vocab_chunk=8, learning_rate=3e-3, seed=7,
    ).init_model()
    ds = DataSet(x, y)
    for step in range(steps):
        model.fit_batch(ds)
        if step % 50 == 0:
            print(f"step {step}: loss {model.score_value:.4f}")

    prompt = corpus(2, 8, seed=9)
    out = np.asarray(generate(model, prompt, 12, temperature=0.0))
    for row in out:
        print("generated:", "".join(ALPHABET[t] for t in row))
    # the grammar is deterministic: continuation quality is measurable
    want = (out[:, 7][:, None] + 1 + np.arange(12)[None, :]) % VOCAB
    acc = float((out[:, 8:] == want).mean())
    print(f"continuation accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
