"""Audio classification from WAV files through the DataVec audio readers.

Generates a tiny labeled tone corpus on disk, reads it back with
SpectrogramRecordReader (stdlib WAV decode + numpy STFT), and trains a
classifier on the spectrogram features.

Run:  python examples/audio_classify.py       (EXAMPLE_QUICK=1 to smoke)
"""

import os
import tempfile
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.datavec import SpectrogramRecordReader, write_wav
from deeplearning4j_tpu.models import SequentialModel
from deeplearning4j_tpu.nn import Adam
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss

QUICK = os.environ.get("EXAMPLE_QUICK", "") not in ("", "0")
RATE = 8000


def make_corpus(root: Path, clips_per_class: int):
    for cls, freq in (("low", 220.0), ("mid", 880.0), ("high", 1760.0)):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(clips_per_class):
            t = np.arange(int(0.25 * RATE)) / RATE
            f = freq * (1 + 0.02 * i)
            wave = 0.5 * np.sin(2 * np.pi * f * t)
            write_wav(d / f"clip{i}.wav", wave.astype(np.float32), RATE)


def main():
    root = Path(tempfile.mkdtemp())
    make_corpus(root, 4 if QUICK else 12)
    rr = SpectrogramRecordReader(
        clip_samples=2000, frame_length=256, frame_step=128
    ).initialize(root)
    feats, labels = [], []
    for spec, label in rr:
        feats.append(spec.reshape(-1))
        labels.append(label)
    x = np.stack(feats)
    x = (x - x.mean()) / (x.std() + 1e-6)
    y = np.eye(rr.num_labels(), dtype=np.float32)[labels]
    print(f"{len(x)} clips, {rr.num_labels()} classes "
          f"({', '.join(rr.labels)}), {x.shape[1]} features")

    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .updater(Adam(1e-2))
        .list()
        .layer(Dense(n_out=32, activation=Activation.RELU))
        .layer(OutputLayer(n_out=rr.num_labels(), loss=Loss.MCXENT,
                           activation=Activation.SOFTMAX))
        .set_input_type(InputType.feed_forward(x.shape[1]))
        .build()
    )
    model = SequentialModel(conf).init()
    model.fit((x, y), epochs=10 if QUICK else 60, batch_size=16)
    acc = model.evaluate(DataSet(x, y)).accuracy()
    print(f"accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
