"""DataVec-role ETL: records, readers, schema, declarative transforms.

Role parity with the reference's `datavec/` tree (SURVEY.md §2.2 "DataVec
(ETL)"): a record abstraction over CSV/lines/collections/images, a typed
`Schema`, a declarative `TransformProcess` of column operations, and the
`RecordReaderDataSetIterator` bridge into the training pipeline.

TPU-native stance: transforms are pure functions over columnar numpy
batches (vectorized), the iterator bridge emits fixed-shape `DataSet`
batches so the compiled train step never recompiles, async prefetch
(`AsyncDataSetIterator`) overlaps host ETL with device steps, and the
common decode chain can leave the host entirely: `datavec/device.py`
lowers a `TransformChain` into the compiled step program so fit()
stages raw uint8 bytes and XLA runs the decode (`device.py` module
docstring has the contract).
"""

from deeplearning4j_tpu.datavec.records import (
    CSVSequenceRecordReader,
    JDBCRecordReader,
    balanced_path_filter,
    load_numeric_csv,
    pattern_label_generator,
    random_path_filter,
    RecordReader,
    CollectionRecordReader,
    CSVRecordReader,
    LineRecordReader,
    ImageRecordReader,
)
from deeplearning4j_tpu.datavec.audio import (
    SpectrogramRecordReader,
    VideoRecordReader,
    WavFileRecordReader,
    read_wav,
    spectrogram,
    write_wav,
)
from deeplearning4j_tpu.datavec.schema import Schema, ColumnType
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.executor import LocalTransformExecutor
from deeplearning4j_tpu.datavec.bridge import (
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datavec.join_reduce import (
    Join,
    JoinType,
    Reducer,
    ReduceOp,
)
from deeplearning4j_tpu.datavec.device import (
    DeviceTransformIterator,
    TransformChain,
    device_transform,
)

__all__ = [
    "DeviceTransformIterator",
    "TransformChain",
    "device_transform",
    "load_numeric_csv",
    "JDBCRecordReader",
    "CSVSequenceRecordReader",
    "Join",
    "JoinType",
    "Reducer",
    "ReduceOp",
    "RecordReader",
    "CollectionRecordReader",
    "CSVRecordReader",
    "LineRecordReader",
    "ImageRecordReader",
    "Schema",
    "ColumnType",
    "TransformProcess",
    "LocalTransformExecutor",
    "RecordReaderDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
    "WavFileRecordReader",
    "SpectrogramRecordReader",
    "VideoRecordReader",
    "read_wav",
    "write_wav",
    "spectrogram",
    "pattern_label_generator",
    "random_path_filter",
    "balanced_path_filter",
]
