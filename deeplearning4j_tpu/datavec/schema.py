"""Typed column schema — the `org.datavec.api.transform.schema.Schema` role.

A schema names and types the columns of a record stream; TransformProcess
steps consume and produce schemas so the output layout of a declarative
pipeline is known statically (reference behavior: each transform maps an
input Schema to an output Schema).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import List, Optional, Sequence


class ColumnType(enum.Enum):
    DOUBLE = "double"
    INTEGER = "integer"
    LONG = "long"
    CATEGORICAL = "categorical"
    STRING = "string"
    TIME = "time"
    BOOLEAN = "boolean"


@dataclasses.dataclass(frozen=True)
class ColumnMeta:
    name: str
    type: ColumnType
    # categorical state space, when type == CATEGORICAL
    categories: Optional[tuple] = None

    def is_numeric(self) -> bool:
        return self.type in (ColumnType.DOUBLE, ColumnType.INTEGER, ColumnType.LONG, ColumnType.BOOLEAN)


class Schema:
    """Ordered, named, typed columns with a builder matching the reference DSL.

    >>> s = (Schema.builder()
    ...      .add_double("sepal_len")
    ...      .add_categorical("species", ["a", "b"])
    ...      .build())
    """

    def __init__(self, columns: Sequence[ColumnMeta]):
        self.columns: List[ColumnMeta] = list(columns)
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        self._index = {c.name: i for i, c in enumerate(self.columns)}

    # --- queries ---------------------------------------------------------
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def num_columns(self) -> int:
        return len(self.columns)

    def index_of(self, name: str) -> int:
        if name not in self._index:
            raise KeyError(f"no column {name!r}; have {self.column_names()}")
        return self._index[name]

    def meta(self, name: str) -> ColumnMeta:
        return self.columns[self.index_of(name)]

    def has_column(self, name: str) -> bool:
        return name in self._index

    # --- serde -----------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(
            [
                {
                    "name": c.name,
                    "type": c.type.value,
                    "categories": list(c.categories) if c.categories else None,
                }
                for c in self.columns
            ]
        )

    @staticmethod
    def from_json(s: str) -> "Schema":
        cols = [
            ColumnMeta(
                d["name"],
                ColumnType(d["type"]),
                tuple(d["categories"]) if d.get("categories") else None,
            )
            for d in json.loads(s)
        ]
        return Schema(cols)

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.columns == other.columns

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(f"{c.name}:{c.type.value}" for c in self.columns) + ")"

    # --- builder ---------------------------------------------------------
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_double(self, *names: str) -> "Schema.Builder":
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.DOUBLE))
            return self

        def add_integer(self, *names: str) -> "Schema.Builder":
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.INTEGER))
            return self

        def add_long(self, *names: str) -> "Schema.Builder":
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.LONG))
            return self

        def add_string(self, *names: str) -> "Schema.Builder":
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.STRING))
            return self

        def add_boolean(self, *names: str) -> "Schema.Builder":
            for n in names:
                self._cols.append(ColumnMeta(n, ColumnType.BOOLEAN))
            return self

        def add_categorical(self, name: str, categories: Sequence[str]) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.CATEGORICAL, tuple(categories)))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()
