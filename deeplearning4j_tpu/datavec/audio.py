"""Audio ETL — the `datavec-data-audio` role (WavFileRecordReader /
NativeAudioRecordReader [U]).

The reference decodes audio through JavaCV/FFmpeg; here WAV decoding is
stdlib (`wave`) + numpy — zero native dependencies for the standard
uncompressed formats (PCM 8/16/32-bit) — and feature extraction
(framing, log-mel-free spectrograms via numpy FFT) happens on the host
so the device step stays a pure matmul program.  Compressed formats
(mp3/ogg/flac) are explicitly gated: decoding them needs codecs this
image does not ship.

Record layouts:
  WavFileRecordReader      -> [samples (T,) or (T,C) float32, label_index]
  SpectrogramRecordReader  -> [spectrogram (frames, bins) float32, label_index]

Labels come from the parent directory name, matching ImageRecordReader /
ParentPathLabelGenerator behavior.
"""

from __future__ import annotations

import os
import wave
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader

_GATED_EXTS = {".mp3", ".ogg", ".flac", ".m4a", ".aac", ".opus"}


def read_wav(path: str | os.PathLike) -> tuple[np.ndarray, int]:
    """Decode a PCM WAV file -> (float32 samples in [-1, 1], sample_rate).

    Mono files give (T,); multi-channel (T, C).
    """
    with wave.open(str(path), "rb") as w:
        n_channels = w.getnchannels()
        width = w.getsampwidth()
        rate = w.getframerate()
        raw = w.readframes(w.getnframes())
    if width == 1:                        # unsigned 8-bit
        x = np.frombuffer(raw, np.uint8).astype(np.float32)
        x = (x - 128.0) / 128.0
    elif width == 2:                      # signed 16-bit
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:                      # signed 32-bit
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    elif width == 3:                      # signed 24-bit, little-endian
        b = np.frombuffer(raw, np.uint8).reshape(-1, 3)
        x = (
            b[:, 0].astype(np.int32)
            | (b[:, 1].astype(np.int32) << 8)
            | (b[:, 2].astype(np.int32) << 16)
        )
        x = np.where(x >= 1 << 23, x - (1 << 24), x).astype(np.float32) / float(
            1 << 23
        )
    else:
        raise ValueError(f"unsupported WAV sample width {width} bytes: {path}")
    if n_channels > 1:
        x = x.reshape(-1, n_channels)
    return x, rate


def write_wav(path: str | os.PathLike, samples: np.ndarray, rate: int) -> None:
    """Inverse of read_wav (16-bit PCM) — used by tests to build fixtures."""
    samples = np.asarray(samples, np.float32)
    n_channels = 1 if samples.ndim == 1 else samples.shape[1]
    pcm = np.clip(samples * 32767.0, -32768, 32767).astype("<i2")
    with wave.open(str(path), "wb") as w:
        w.setnchannels(n_channels)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())


def spectrogram(
    samples: np.ndarray,
    *,
    frame_length: int = 256,
    frame_step: int = 128,
    window: str = "hann",
    log: bool = True,
    epsilon: float = 1e-6,
) -> np.ndarray:
    """Magnitude (or log-magnitude) STFT spectrogram, (frames, bins).

    Static shapes: the frame count is fully determined by (len, length,
    step), so batches of equal-length clips compile to one XLA program
    downstream.
    """
    x = np.asarray(samples, np.float32)
    if x.ndim == 2:
        x = x.mean(axis=1)                 # downmix to mono for features
    n = len(x)
    if n < frame_length:
        x = np.pad(x, (0, frame_length - n))
        n = frame_length
    n_frames = 1 + (n - frame_length) // frame_step
    idx = (
        np.arange(frame_length)[None, :]
        + frame_step * np.arange(n_frames)[:, None]
    )
    frames = x[idx]
    if window == "hann":
        frames = frames * np.hanning(frame_length)[None, :]
    elif window != "none":
        raise ValueError(f"unknown window {window!r}")
    mag = np.abs(np.fft.rfft(frames, axis=1)).astype(np.float32)
    return np.log(mag + epsilon) if log else mag


class WavFileRecordReader(RecordReader):
    """Directory-tree WAV reader with parent-dir labels.

    `clip_samples` pads/truncates every clip to a fixed length so the
    resulting batches are static-shaped (XLA requirement); None keeps
    ragged native lengths (host-side processing only).
    """

    def __init__(
        self,
        *,
        clip_samples: Optional[int] = None,
        shuffle_seed: Optional[int] = None,
    ):
        self.clip_samples = clip_samples
        self._shuffle_seed = shuffle_seed
        self._files: List[Path] = []
        self.labels: List[str] = []
        self.sample_rate: Optional[int] = None

    def initialize(self, root: str | os.PathLike) -> "WavFileRecordReader":
        root = Path(root)
        # one case-normalized walk: no duplicates on case-insensitive
        # filesystems, no misses on mixed-case extensions
        self._files = sorted(
            p for p in root.rglob("*")
            if p.is_file() and p.suffix.lower() == ".wav"
        )
        if not self._files:
            gated = sorted(
                p for p in root.rglob("*") if p.suffix.lower() in _GATED_EXTS
            )
            if gated:
                raise ValueError(
                    f"only compressed audio ({gated[0].suffix}, ...) found "
                    f"under {root}; this build decodes PCM WAV only — "
                    "transcode with ffmpeg first"
                )
            raise FileNotFoundError(f"no .wav files under {root}")
        self.labels = sorted({p.parent.name for p in self._files})
        if self._shuffle_seed is not None:
            import random

            random.Random(self._shuffle_seed).shuffle(self._files)
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def _fit_length(self, x: np.ndarray) -> np.ndarray:
        if self.clip_samples is None:
            return x
        t = self.clip_samples
        if len(x) >= t:
            return x[:t]
        pad = [(0, t - len(x))] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad)

    def __iter__(self):
        if not self._files:
            raise RuntimeError("call initialize(root) first")
        label_idx = {l: i for i, l in enumerate(self.labels)}
        for p in self._files:
            x, rate = read_wav(p)
            self.sample_rate = rate
            yield [self._fit_length(x), label_idx[p.parent.name]]


class SpectrogramRecordReader(WavFileRecordReader):
    """WAV reader emitting STFT spectrogram features per clip — the
    reference's audio-feature pipeline role, computed with numpy FFT."""

    def __init__(
        self,
        *,
        clip_samples: int,
        frame_length: int = 256,
        frame_step: int = 128,
        log: bool = True,
        shuffle_seed: Optional[int] = None,
    ):
        super().__init__(clip_samples=clip_samples, shuffle_seed=shuffle_seed)
        self.frame_length = frame_length
        self.frame_step = frame_step
        self.log = log

    def __iter__(self):
        for samples, label in super().__iter__():
            feats = spectrogram(
                samples,
                frame_length=self.frame_length,
                frame_step=self.frame_step,
                log=self.log,
            )
            yield [feats, label]


# VideoRecordReader moved to datavec.video (real MJPEG-AVI decoding);
# re-exported here for backwards compatibility with the old gate location
from deeplearning4j_tpu.datavec.video import VideoRecordReader  # noqa: E402,F401
