"""Transform executors — the `LocalTransformExecutor` /
`SparkTransformExecutor` roles (SURVEY.md §2.2 DataVec).

The reference executes a TransformProcess either serially in-process or
as a Spark job whose serialized DAG ships to cluster executors.  The
TPU-framework equivalent of that second tier: the process serializes to
JSON (TransformProcess.to_json), record partitions fan out to worker
PROCESSES (plain subprocesses running this module, fed JSON over stdin —
no dependence on the parent's __main__, so it works from scripts, REPLs
and notebooks alike, and no fork of the JAX-threaded parent), each
worker rebuilds the pipeline from JSON and transforms its partition.
Every built-in step is per-row (aggregations live in
datavec.join_reduce), so partitioning is semantics-preserving, including
row filters (counts just concatenate).  Worker interpreter startup is
the Spark-executor-JVM cost of this tier, amortized over cluster-scale
ETL inputs.

`derive_column` steps carry an arbitrary Python fn that does not
serialize (reference parity: custom transforms round-trip by class name
only) — those pipelines run serially with a warning rather than failing.

Economics (same as any process-shipping ETL tier, Spark included): each
record pays a JSON round-trip, so the fan-out wins when per-row transform
work dominates serialization — long step chains, string parsing, joins of
wide rows — and loses on trivial scalar math.  num_workers=0 (serial) is
always correct; the default min_records_per_worker guard keeps small
inputs serial automatically.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import warnings
from typing import List

Records = List[list]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class LocalTransformExecutor:
    """Executor facade: `execute(process, records)` mirrors the reference's
    `LocalTransformExecutor.execute(inputData, transformProcess)`; pass
    num_workers > 1 for the partition-parallel (Spark-role) path."""

    @staticmethod
    def execute(process, records: Records, num_workers: int = 0,
                min_records_per_worker: int = 256,
                timeout: float = 600.0) -> Records:
        parallel = (
            num_workers > 1
            and len(records) >= num_workers * min_records_per_worker
        )
        if parallel and any(
            st.spec.get("kind") in (
                "derive_column", "convert_to_sequence", "offset_sequence",
                "trim_sequence", "sequence_moving_window_reduce",
            ) for st in process.steps
        ):
            warnings.warn(
                "TransformProcess contains a derive_column (opaque Python "
                "fn) or sequence step (grouping crosses partition "
                "boundaries); executing serially",
                stacklevel=2,
            )
            parallel = False
        if not parallel:
            return process.execute(records)

        tp_json = process.to_json()
        n = num_workers
        size = (len(records) + n - 1) // n
        parts = [records[i : i + size] for i in range(0, len(records), size)]
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        # run the worker as a FILE with -S: -m would import the package
        # __init__ chain (which reaches jax) before this module even runs,
        # and site initialization itself can be seconds on hosts whose
        # sitecustomize registers accelerator plugins.  The worker needs
        # only the stdlib plus two pure-stdlib modules loaded by path.
        procs = [
            subprocess.Popen(
                [sys.executable, "-S", os.path.abspath(__file__)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, env=env, text=True,
            )
            for _ in parts
        ]
        # feed + drain every worker CONCURRENTLY — payloads exceed pipe
        # buffers, so sequential communicate() calls would serialize the
        # whole fan-out (worker k+1 idle until worker k exits)
        import threading

        results: list = [None] * len(procs)

        def pump(i, p, part):
            try:
                results[i] = p.communicate(
                    json.dumps({"process": tp_json, "records": part}),
                    timeout=timeout,
                )
            except subprocess.TimeoutExpired:
                pass  # results[i] stays None -> reported as timed out

        threads = [
            threading.Thread(target=pump, args=(i, p, part), daemon=True)
            for i, (p, part) in enumerate(zip(procs, parts))
        ]
        try:
            for t in threads:
                t.start()
            # one shared deadline: a slow worker must not double the
            # effective bound to ~2x timeout across the join loop
            deadline = time.monotonic() + (timeout or 0)
            for t in threads:
                t.join(
                    timeout=max(0.0, deadline - time.monotonic())
                    if timeout else None
                )
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            # once the processes are dead the pumps finish promptly;
            # re-join so results[] is settled before it is read (a worker
            # finishing just under the deadline must not be misreported
            # as timed out)
            for t in threads:
                t.join(timeout=10)
            # reap killed workers and close their pipes: the pump's
            # communicate() raised TimeoutExpired before doing either —
            # an unreaped kill leaves a zombie plus Popen/pipe
            # ResourceWarnings at GC
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass
                for f in (p.stdin, p.stdout, p.stderr):
                    if f is not None:
                        try:
                            f.close()
                        except Exception:
                            pass
        out: Records = []
        errors = []
        for p, res in zip(procs, results):
            if res is None or p.returncode != 0:
                errors.append(
                    (res[1] if res else "worker timed out")[-2000:]
                )
                continue
            out.extend(json.loads(res[0]))
        if errors:
            raise RuntimeError(
                "transform worker(s) failed:\n" + "\n---\n".join(errors)
            )
        return out


def _load_transform_module():
    """Import datavec.transform WITHOUT the package __init__ chain — that
    chain reaches `import jax` (bridge -> data.iterator), a multi-second
    cost per worker that would often exceed the serial transform time the
    fan-out exists to beat.  schema/transform themselves are pure stdlib,
    so in a fresh interpreter they load by file path under stub parent
    packages; a process that already imported the real package just uses
    it."""
    if "deeplearning4j_tpu.datavec.transform" in sys.modules:
        return sys.modules["deeplearning4j_tpu.datavec.transform"]
    if "deeplearning4j_tpu" in sys.modules:
        from deeplearning4j_tpu.datavec import transform

        return transform
    import importlib.util
    import types

    base = os.path.dirname(os.path.abspath(__file__))
    for name in ("deeplearning4j_tpu", "deeplearning4j_tpu.datavec"):
        stub = types.ModuleType(name)
        stub.__path__ = []
        sys.modules.setdefault(name, stub)
    for mod in ("schema", "transform"):
        full = f"deeplearning4j_tpu.datavec.{mod}"
        spec = importlib.util.spec_from_file_location(
            full, os.path.join(base, f"{mod}.py")
        )
        m = importlib.util.module_from_spec(spec)
        sys.modules[full] = m
        spec.loader.exec_module(m)
    return sys.modules["deeplearning4j_tpu.datavec.transform"]


def _worker_main() -> None:
    payload = json.load(sys.stdin)
    transform = _load_transform_module()
    tp = transform.TransformProcess.from_json(payload["process"])
    json.dump(tp.execute(payload["records"]), sys.stdout)


if __name__ == "__main__":
    _worker_main()
