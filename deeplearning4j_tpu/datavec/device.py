"""Device-compiled data pipeline — DataVec transforms lowered into XLA.

PR 5's `PrefetchIterator` only *hides* host decode behind the running
step: the producer thread still burns a core per device on cast /
normalize / resize / one-hot, and the moment there is no spare core
(BENCH_SCALING's n=2 row) the overlap collapses.  Following the Julia→
TPU full-compilation paper (PAPERS.md), this module moves the decode
itself onto the device: the common DataVec-style transform chain is
lowered to a pure ``device_decode(step_i, raw_features, raw_labels) ->
(features, labels, features_mask, labels_mask)`` function that the fit
paths trace INTO the training-step program — one compiled XLA
computation does decode + forward + backward + update, and the host's
per-batch job shrinks to slicing raw uint8 bytes.

Three pieces:

- **Transform specs** (`Scale`, `Standardize`, `MinMaxScale`,
  `CenterCrop`, `RandomCrop`, `RandomFlip`, `MeanPool`, `OneHot`,
  `PadToBucket`, `Custom`): each knows a numpy **host** application
  (the fallback path and the parity reference) and a jax **device**
  application (traced into the step program).  Random transforms
  (crop/flip) draw from a key folded from the step counter, so the
  augmentation stream is deterministic per step on BOTH paths.
- **`TransformChain`** + **`try_lower()`**: the compiler.  A chain
  whose every spec is device-lowerable compiles to a `DeviceDecode`;
  anything else (e.g. a `Custom` transform not marked
  ``@device_transform``) returns a reason and the fit paths fall back
  to host transforms — same numerics, no fusion.
- **`DeviceTransformIterator`** + the advertisement protocol
  (`chain_of` / `raw_feed`): an iterator that *advertises* a chain via
  a ``device_chain`` attribute and raw batches via ``raw()``.  Its own
  ``__iter__`` applies the chain on the host, so the iterator works
  everywhere unchanged; `Model.fit` detects the chain, lowers it, and
  switches the feed to tagged raw batches when fusion is possible.

Trace-purity contract: every ``device_apply`` body (and any function a
user marks with ``@device_transform``) is a JIT SCOPE — tpulint's TP
family lints these bodies exactly like ``@jax.jit`` functions, so an
impure transform fails lint, not trace.
"""

from __future__ import annotations

import dataclasses
import logging
import time
import zlib
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, copy_tags
from deeplearning4j_tpu.data.iterator import DataSetIterator

log = logging.getLogger("deeplearning4j_tpu")


def device_transform(fn: Callable) -> Callable:
    """Mark `fn(x, key)` as safe to trace into the fused decode program.

    The marker is what `try_lower` checks on `Custom` transforms, and
    what tpulint keys on: a ``@device_transform`` body is a jit scope —
    the TP trace-purity rules apply to it, so `time.time()` / prints /
    global mutation inside a transform fail LINT instead of silently
    freezing at trace time."""
    fn._dl4jtpu_device_transform = True
    return fn


def _array_fp(a) -> tuple:
    """Stable fingerprint of a constant array baked into the program."""
    a = np.asarray(a)
    return (a.shape, str(a.dtype), zlib.crc32(np.ascontiguousarray(a).tobytes()))


class NotLowerable(Exception):
    """A chain (or one spec of it) has no device lowering; `.reason`
    says why — the fit paths log it and fall back to host transforms."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class DeviceTransform:
    """One stage of a decode chain.  Both applications take and return
    ``(array, mask)`` so mask-producing stages (`PadToBucket`) compose
    with mask-oblivious ones; `key` is None unless ``needs_key``."""

    needs_key = False

    def host_apply(self, x, mask, key):
        raise NotImplementedError

    def device_apply(self, x, mask, key):
        raise NotImplementedError

    def fingerprint(self) -> tuple:
        return (type(self).__name__,) + self._fp()

    def _fp(self) -> tuple:
        return ()

    def check_lowerable(self) -> None:
        """Raise NotLowerable when this spec cannot run on device."""


@dataclasses.dataclass
class Scale(DeviceTransform):
    """``x.astype(f32) * scale + offset`` — the ImagePreProcessingScaler
    lowering (uint8 [0,255] -> [lo,hi] floats)."""

    scale: float = 1.0 / 255.0
    offset: float = 0.0

    @device_transform
    def device_apply(self, x, mask, key):
        import jax.numpy as jnp

        return (x.astype(jnp.float32) * jnp.float32(self.scale)
                + jnp.float32(self.offset)), mask

    def host_apply(self, x, mask, key):
        return (x.astype(np.float32) * np.float32(self.scale)
                + np.float32(self.offset)), mask

    def _fp(self):
        return (float(self.scale), float(self.offset))


@dataclasses.dataclass
class Standardize(DeviceTransform):
    """``(x - mean) / std`` with per-feature stats — the
    NormalizerStandardize lowering (stats fit on host, applied on
    device as baked-in constants)."""

    mean: np.ndarray = None
    std: np.ndarray = None

    @device_transform
    def device_apply(self, x, mask, key):
        import jax.numpy as jnp

        return ((x.astype(jnp.float32) - jnp.asarray(self.mean, jnp.float32))
                / jnp.asarray(self.std, jnp.float32)), mask

    def host_apply(self, x, mask, key):
        return ((x.astype(np.float32) - np.asarray(self.mean, np.float32))
                / np.asarray(self.std, np.float32)), mask

    def _fp(self):
        return (_array_fp(self.mean), _array_fp(self.std))


@dataclasses.dataclass
class MinMaxScale(DeviceTransform):
    """Per-feature min/max scale into [lo, hi] — the
    NormalizerMinMaxScaler lowering (same epsilon + op order)."""

    min: np.ndarray = None
    max: np.ndarray = None
    lo: float = 0.0
    hi: float = 1.0

    @device_transform
    def device_apply(self, x, mask, key):
        import jax.numpy as jnp

        mn = jnp.asarray(self.min, jnp.float32)
        rng = jnp.maximum(jnp.asarray(self.max, jnp.float32) - mn, 1e-12)
        return ((x.astype(jnp.float32) - mn) / rng
                * jnp.float32(self.hi - self.lo) + jnp.float32(self.lo)), mask

    def host_apply(self, x, mask, key):
        mn = np.asarray(self.min, np.float32)
        rng = np.maximum(np.asarray(self.max, np.float32) - mn, 1e-12)
        return ((x.astype(np.float32) - mn) / rng
                * np.float32(self.hi - self.lo) + np.float32(self.lo)), mask

    def _fp(self):
        return (_array_fp(self.min), _array_fp(self.max),
                float(self.lo), float(self.hi))


@dataclasses.dataclass
class CenterCrop(DeviceTransform):
    """Static center crop of the two spatial axes of an NHWC batch."""

    height: int = 0
    width: int = 0

    @device_transform
    def device_apply(self, x, mask, key):
        top = (x.shape[1] - self.height) // 2
        left = (x.shape[2] - self.width) // 2
        return x[:, top:top + self.height, left:left + self.width], mask

    def host_apply(self, x, mask, key):
        top = (x.shape[1] - self.height) // 2
        left = (x.shape[2] - self.width) // 2
        return x[:, top:top + self.height, left:left + self.width], mask

    def _fp(self):
        return (int(self.height), int(self.width))


@dataclasses.dataclass
class RandomCrop(DeviceTransform):
    """Random crop of the spatial axes (one offset per batch, drawn
    from the step key — deterministic per step)."""

    height: int = 0
    width: int = 0
    needs_key = True

    @device_transform
    def device_apply(self, x, mask, key):
        import jax
        import jax.numpy as jnp
        from jax import lax

        kt, kl = jax.random.split(key)
        top = jax.random.randint(kt, (), 0, x.shape[1] - self.height + 1)
        left = jax.random.randint(kl, (), 0, x.shape[2] - self.width + 1)
        x = lax.dynamic_slice_in_dim(x, top, self.height, axis=1)
        x = lax.dynamic_slice_in_dim(x, left, self.width, axis=2)
        return jnp.asarray(x), mask

    def host_apply(self, x, mask, key):
        # eager jax.random with the SAME key derivation: host fallback
        # and parity tests draw the exact offsets the device draws
        import jax

        kt, kl = jax.random.split(key)
        top = int(jax.random.randint(kt, (), 0, x.shape[1] - self.height + 1))
        left = int(jax.random.randint(kl, (), 0, x.shape[2] - self.width + 1))
        return x[:, top:top + self.height, left:left + self.width], mask

    def _fp(self):
        return (int(self.height), int(self.width))


@dataclasses.dataclass
class RandomFlip(DeviceTransform):
    """Per-example coin-flip reversal of one axis (horizontal flip
    augment at the default ``axis=2`` of NHWC)."""

    prob: float = 0.5
    axis: int = 2
    needs_key = True

    @device_transform
    def device_apply(self, x, mask, key):
        import jax
        import jax.numpy as jnp

        coin = jax.random.bernoulli(key, self.prob, (x.shape[0],))
        sel = coin.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(sel, jnp.flip(x, self.axis), x), mask

    def host_apply(self, x, mask, key):
        import jax

        coin = np.asarray(jax.random.bernoulli(key, self.prob, (x.shape[0],)))
        sel = coin.reshape((-1,) + (1,) * (x.ndim - 1))
        return np.where(sel, np.flip(x, self.axis), x), mask

    def _fp(self):
        return (float(self.prob), int(self.axis))


@dataclasses.dataclass
class MeanPool(DeviceTransform):
    """Average-pool downscale of the spatial axes of an NHWC batch
    (window (wh, ww) must divide H and W); ``collapse_channels`` also
    means over C and keeps a singleton channel — the cheap
    decode-resize used by the camera-wire bench feed."""

    window: tuple = (2, 2)
    collapse_channels: bool = False

    @device_transform
    def device_apply(self, x, mask, key):
        import jax.numpy as jnp

        b, h, w, c = x.shape
        wh, ww = self.window
        x = x.astype(jnp.float32).reshape(b, h // wh, wh, w // ww, ww, c)
        if self.collapse_channels:
            return x.mean(axis=(2, 4, 5))[..., None], mask
        return x.mean(axis=(2, 4)), mask

    def host_apply(self, x, mask, key):
        b, h, w, c = x.shape
        wh, ww = self.window
        x = x.astype(np.float32).reshape(b, h // wh, wh, w // ww, ww, c)
        if self.collapse_channels:
            return x.mean(axis=(2, 4, 5), dtype=np.float32)[..., None], mask
        return x.mean(axis=(2, 4), dtype=np.float32), mask

    def _fp(self):
        return (tuple(self.window), bool(self.collapse_channels))


@dataclasses.dataclass
class OneHot(DeviceTransform):
    """Integer class ids -> one-hot float32 rows (label-side)."""

    num_classes: int = 0

    @device_transform
    def device_apply(self, x, mask, key):
        import jax

        return jax.nn.one_hot(x, self.num_classes, dtype="float32"), mask

    def host_apply(self, x, mask, key):
        ids = np.asarray(x).astype(np.int64)
        return np.eye(self.num_classes, dtype=np.float32)[ids], mask

    def _fp(self):
        return (int(self.num_classes),)


@dataclasses.dataclass
class PadToBucket(DeviceTransform):
    """Pad the time axis up to the bucketing quantum
    (`flags.bucket_length`) and emit/extend the mask marking real
    steps — the recompile-hygiene transform: a mixed-length corpus
    compiles ceil(max_len/quantum) programs instead of one per length.

    ``quantum=None`` resolves ``flags.sequence_bucket_size`` ONCE at
    lowering time (host-side), never inside the traced body."""

    quantum: Optional[int] = None
    axis: int = 1
    _resolved: Optional[int] = dataclasses.field(default=None, repr=False)

    def resolved_quantum(self) -> int:
        if self._resolved is None:
            from deeplearning4j_tpu.runtime.flags import environment

            # only None means "resolve from flags": an explicit 0 must
            # hit bucket_length's positive-quantum validation, not be
            # silently replaced by the default
            self._resolved = (environment().sequence_bucket_size
                              if self.quantum is None
                              else int(self.quantum))
        return self._resolved

    def _target(self, length: int) -> int:
        from deeplearning4j_tpu.runtime.flags import bucket_length

        return bucket_length(length, self.resolved_quantum())

    @device_transform
    def device_apply(self, x, mask, key):
        import jax.numpy as jnp

        t = x.shape[self.axis]
        pad = self._target(t) - t
        if mask is None:
            mask = jnp.ones((x.shape[0], t), jnp.float32)
        if pad == 0:
            return x, mask
        widths = [(0, 0)] * x.ndim
        widths[self.axis] = (0, pad)
        return jnp.pad(x, widths), jnp.pad(mask, ((0, 0), (0, pad)))

    def host_apply(self, x, mask, key):
        t = x.shape[self.axis]
        pad = self._target(t) - t
        if mask is None:
            mask = np.ones((x.shape[0], t), np.float32)
        if pad == 0:
            return x, mask
        widths = [(0, 0)] * x.ndim
        widths[self.axis] = (0, pad)
        return np.pad(x, widths), np.pad(mask, ((0, 0), (0, pad)))

    def _fp(self):
        return (self.resolved_quantum(), int(self.axis))


@dataclasses.dataclass
class Custom(DeviceTransform):
    """A user transform ``fn(x, key) -> x``.  Lowerable only when the
    function is marked ``@device_transform`` (the marker is the
    author's promise the body is pure jax — and tpulint's cue to lint
    it as a jit scope)."""

    fn: Callable = None
    needs_key = True

    def check_lowerable(self) -> None:
        if not getattr(self.fn, "_dl4jtpu_device_transform", False):
            name = getattr(self.fn, "__qualname__", repr(self.fn))
            raise NotLowerable(
                f"custom transform {name} is not marked @device_transform"
            )

    def device_apply(self, x, mask, key):
        return self.fn(x, key), mask

    def host_apply(self, x, mask, key):
        return np.asarray(self.fn(x, key)), mask

    def _fp(self):
        # qualname alone collides for distinct closures from the same
        # factory (same code, different captured values) — and the
        # fused step-fn cache keys on this fingerprint, so a collision
        # would silently run the FIRST closure's transform.  id(fn) is
        # sound as the tiebreaker: every cached step program keeps its
        # DeviceDecode (and therefore this fn) alive through its
        # closure, so a live cache entry's id can never be reused.
        code = getattr(self.fn, "__code__", None)
        return (getattr(self.fn, "__module__", "?"),
                getattr(self.fn, "__qualname__", repr(self.fn)),
                zlib.crc32(code.co_code) if code is not None else 0,
                id(self.fn))


@dataclasses.dataclass
class TransformChain:
    """An ordered feature-transform list + label-transform list, plus
    the augmentation seed the per-step keys fold from."""

    features: tuple = ()
    labels: tuple = ()
    seed: int = 0

    def __post_init__(self):
        self.features = tuple(self.features)
        self.labels = tuple(self.labels)

    @property
    def specs(self) -> tuple:
        return self.features + self.labels

    def needs_key(self) -> bool:
        return any(s.needs_key for s in self.specs)

    def fingerprint(self) -> tuple:
        return (
            tuple(s.fingerprint() for s in self.features),
            tuple(s.fingerprint() for s in self.labels),
            int(self.seed),
        )


def _apply_chain(chain: TransformChain, step_i, feats, labs, *,
                 device: bool, fmask0=None, lmask0=None):
    """Shared traversal of both applications: per-spec keys fold from
    (seed, step_i, spec position), so host fallback, parity tests and
    the fused program draw identical augmentation streams.  fmask0 /
    lmask0 seed the mask threading — the HOST path passes the batch's
    own masks through (mask-producing specs extend them); the fused
    device path never sees a masked raw batch (the fit routing refuses
    fusion there)."""
    base = None
    if chain.needs_key():
        import jax

        base = jax.random.fold_in(jax.random.key(chain.seed), step_i)

    def run(specs, x, salt, mask):
        import jax

        for i, spec in enumerate(specs):
            k = (jax.random.fold_in(base, salt + i)
                 if spec.needs_key else None)
            if device:
                x, mask = spec.device_apply(x, mask, k)
            else:
                x, mask = spec.host_apply(x, mask, k)
        return x, mask

    feats, fmask = run(chain.features, feats, 0, fmask0)
    labs, lmask = run(chain.labels, labs, 1000, lmask0)
    return feats, labs, fmask, lmask


class DeviceDecode:
    """A lowered chain: ``fn`` is the pure traced decode the fit paths
    compose in front of the step body; ``host()`` is the numpy
    reference the parity tests diff against; ``calibrated_seconds``
    measures the standalone jitted decode once per input signature (the
    fused program hides the stage, so attribution uses this calibrated
    per-signature cost)."""

    def __init__(self, chain: TransformChain):
        self.chain = chain
        self.fingerprint = chain.fingerprint()
        self._jit_fn = None
        self._calib: dict = {}

    def fn(self, step_i, raw_feats, raw_labels):
        """Traced decode body (called inside the fused step program)."""
        return _apply_chain(self.chain, step_i, raw_feats, raw_labels,
                            device=True)

    def host(self, step_i, batch: DataSet) -> DataSet:
        """Numpy reference application (fallback path semantics).  The
        batch's own masks thread through the chain — preserved when no
        spec touches them, extended by mask-producing specs — matching
        what the pre-chain iterator stack (e.g. NormalizingIterator)
        would have handed the fit loop."""
        feats, labs, fmask, lmask = _apply_chain(
            self.chain, step_i, batch.features, batch.labels,
            device=False, fmask0=batch.features_mask,
            lmask0=batch.labels_mask,
        )
        out = copy_tags(batch, DataSet(
            np.asarray(feats), np.asarray(labs),
            None if fmask is None else np.asarray(fmask),
            None if lmask is None else np.asarray(lmask),
        ))
        # attribution tags (_etl_source) survive the decode; the
        # raw-routing tag must not — this output IS the decoded batch
        out._raw_for_device_decode = False
        return out

    def jitted(self):
        if self._jit_fn is None:
            import jax

            from deeplearning4j_tpu.observe import cost

            # the standalone lowered decode joins the compiled-program
            # registry too (kind="decode"), so /api/programs attributes
            # the decode stage's FLOPs/bytes next to the step programs
            self._jit_fn = cost.register_attr_program(
                self, "_jit_fn", "decode", ("decode", self.fingerprint),
                jax.jit(self.fn),
            )
        return self._jit_fn

    def calibrated_seconds(self, feats, labs) -> float:
        """Measured standalone decode seconds for this input signature
        (cached; first call compiles + times one warm run)."""
        key = (tuple(np.shape(feats)), str(getattr(feats, "dtype", "")),
               tuple(np.shape(labs)), str(getattr(labs, "dtype", "")))
        t = self._calib.get(key)
        if t is None:
            import jax
            import jax.numpy as jnp

            fn = self.jitted()
            si = jnp.uint32(0)
            jax.block_until_ready(fn(si, feats, labs))   # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(fn(si, feats, labs))
            t = time.perf_counter() - t0
            self._calib[key] = t
        return t


def try_lower(chain: TransformChain):
    """Compile `chain` to a DeviceDecode.  Returns ``(decode, None)``
    or ``(None, reason)`` when any spec refuses to lower — the caller
    logs the reason and keeps the host path.

    The lowering is memoized on the chain object: every fit() re-runs
    this decision, and a fresh DeviceDecode per fit would re-pay the
    standalone decode calibration (an XLA compile + two timed device
    runs per input signature) for a result that cannot change — the
    fingerprint, including PadToBucket's flag resolution, is sticky
    per spec instance."""
    if not isinstance(chain, TransformChain):
        return None, f"not a TransformChain: {type(chain).__name__}"
    cached = getattr(chain, "_lowered", None)
    if cached is not None:
        return cached, None
    try:
        for spec in chain.specs:
            if not isinstance(spec, DeviceTransform):
                raise NotLowerable(
                    f"unknown transform type {type(spec).__name__}"
                )
            spec.check_lowerable()
    except NotLowerable as e:
        return None, e.reason
    decode = DeviceDecode(chain)
    chain._lowered = decode
    return decode, None


# -- iterator protocol ----------------------------------------------------

class DeviceTransformIterator(DataSetIterator):
    """Attach a TransformChain to a raw-batch iterator.

    Iterating it applies the chain ON THE HOST (per-batch step index
    keys) — drop-in anywhere a DataSetIterator goes.  It also
    advertises the chain (``device_chain``) and the raw feed
    (``raw()``), which is what `Model.fit` keys on to lower the chain
    into the step program and pull raw uint8 bytes instead."""

    def __init__(self, base: DataSetIterator, chain: TransformChain):
        self._base = base
        self._chain = chain
        self._decode = DeviceDecode(chain)
        self._step = 0

    @property
    def device_chain(self) -> TransformChain:
        return self._chain

    def raw(self) -> DataSetIterator:
        return self._base

    def next_decode_step(self) -> int:
        """The ONE per-batch augmentation counter for this iterator:
        host iteration and the raw feed both draw from it, so the
        fused program and the host fallback fold identical keys no
        matter how fits, evaluates and raw pulls interleave."""
        s = self._step
        self._step += 1
        return s

    @property
    def batch_size(self) -> int:
        return getattr(self._base, "batch_size", 0)

    def reset(self) -> None:
        if hasattr(self._base, "reset"):
            self._base.reset()

    def __iter__(self):
        for batch in self._base:
            # host() copy_tags the attribution tags forward
            yield self._decode.host(self.next_decode_step(), batch)


def chain_of(iterator) -> Optional[TransformChain]:
    """The TransformChain an iterator advertises, or None.  The
    protocol is duck-typed: a ``device_chain`` attribute holding a
    TransformChain plus a ``raw()`` method yielding undecoded
    batches."""
    chain = getattr(iterator, "device_chain", None)
    if isinstance(chain, TransformChain) and hasattr(iterator, "raw"):
        return chain
    return None


class _RawFeed(DataSetIterator):
    """The raw-byte feed of an advertising iterator: yields shallow
    views of the base iterator's batches tagged
    ``_raw_for_device_decode`` so the fit chokepoints route them to
    the fused decode+step program.  A batch that is not a plain
    DataSet (slotted/frozen batch types) is decoded ON THE HOST here
    instead — once the feed is swapped to raw, an untagged raw batch
    must never reach the step undecoded.  Reset delegates to the
    advertising wrapper (which owns the base)."""

    def __init__(self, owner, decode: Optional["DeviceDecode"] = None):
        self._owner = owner
        self._raw = owner.raw()
        self._decode = decode
        self._step = 0

    def _next_step(self) -> int:
        """Per-batch augmentation counter: the owner's shared one when
        it keeps one (DeviceTransformIterator), else feed-local."""
        nxt = getattr(self._owner, "next_decode_step", None)
        if nxt is not None:
            return nxt()
        s = self._step
        self._step += 1
        return s

    @property
    def batch_size(self) -> int:
        return getattr(self._owner, "batch_size", 0)

    def reset(self) -> None:
        if hasattr(self._owner, "reset"):
            self._owner.reset()

    def _host_decode(self):
        if self._decode is None:
            self._decode = DeviceDecode(chain_of(self._owner))
        return self._decode

    def __iter__(self):
        for batch in self._raw:
            i = self._next_step()
            if (isinstance(batch, DataSet)
                    and batch.features_mask is None
                    and batch.labels_mask is None):
                # tag a shallow view (same arrays), never the base
                # object: in-memory bases re-yield the same batch
                # objects across fits, and a sticky tag would
                # misattribute their bytes to the raw-feed H2D series
                # on later non-fused runs
                batch = copy_tags(batch, DataSet(
                    batch.features, batch.labels,
                    batch.features_mask, batch.labels_mask,
                ))
                batch._raw_for_device_decode = True
                # the augmentation key index the fused program folds —
                # carried on the batch so fused and host paths draw
                # from the SAME counter (model.iteration needn't align
                # with feed position after evaluate()/reuse)
                batch._decode_step = i
            else:
                # masked raw batches can never fuse (the fused program
                # stages features/labels only) — decode them here,
                # while still numpy; a tagged masked batch would be
                # prefetch-staged to the device raw and then pay a
                # hidden D2H for its per-step host decode.  Foreign
                # batch types (slotted/frozen, or not DataSet-shaped)
                # likewise host-decode: once the feed is raw, an
                # untagged raw batch must never reach the step
                # undecoded.
                batch = self._host_decode().host(i, batch)
            yield batch


def raw_feed(iterator, decode: Optional[DeviceDecode] = None
             ) -> DataSetIterator:
    return _RawFeed(iterator, decode)
