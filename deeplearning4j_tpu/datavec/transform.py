"""Declarative column transforms — the `TransformProcess` role.

Reference: `org.datavec.api.transform.TransformProcess` — a builder of
column operations, each mapping (Schema, records) → (Schema, records),
executed by a local or Spark executor (SURVEY.md §2.2).  Here the executor
is local and vectorized where possible; the Spark tier's role (cluster ETL)
belongs to the data-parallel input pipeline, not a JVM cluster.

Each step is (schema_fn, records_fn); the process composes them and exposes
`final_schema` statically — same contract as the reference, so a pipeline's
output layout is known before any data flows.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema

Records = List[list]


class _Step:
    def __init__(self, name: str, schema_fn, records_fn, spec: dict):
        self.name = name
        self.schema_fn = schema_fn
        self.records_fn = records_fn
        self.spec = spec  # JSON-serializable description


class TransformProcess:
    """Composed, schema-checked column pipeline with a builder DSL."""

    def __init__(self, initial_schema: Schema, steps: Sequence[_Step]):
        self.initial_schema = initial_schema
        self.steps = list(steps)
        # propagate schemas eagerly: config errors surface at build time,
        # matching the reference's behavior.
        s = initial_schema
        self._schemas = [s]
        for st in self.steps:
            s = st.schema_fn(s)
            self._schemas.append(s)

    @property
    def final_schema(self) -> Schema:
        return self._schemas[-1]

    #: builder step kinds that operate on WHOLE sequences (after
    #: convert_to_sequence the record stream is List[sequence] =
    #: List[List[row]]; plain column steps map over each sequence)
    _SEQ_KINDS = frozenset({
        "convert_to_sequence", "offset_sequence", "trim_sequence",
        "sequence_moving_window_reduce",
    })

    def execute(self, records: Records) -> Records:
        out = [list(r) for r in records]
        seq_mode = False
        for st, schema in zip(self.steps, self._schemas[:-1]):
            kind = st.spec.get("kind")
            if kind == "convert_to_sequence":
                out = st.records_fn(schema, out)
                seq_mode = True
            elif kind in self._SEQ_KINDS:
                out = st.records_fn(schema, out)
            elif seq_mode:
                out = [st.records_fn(schema, seq) for seq in out]
                # row filters may empty a sequence entirely
                out = [seq for seq in out if seq]
            else:
                out = st.records_fn(schema, out)
        return out

    @property
    def emits_sequences(self) -> bool:
        """True when execute() returns List[sequence] (the reference's
        convertToSequence switches the pipeline to sequence records)."""
        return any(
            st.spec.get("kind") == "convert_to_sequence" for st in self.steps
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "initial_schema": json.loads(self.initial_schema.to_json()),
                "steps": [s.spec for s in self.steps],
            }
        )

    @staticmethod
    def from_json(text: str) -> "TransformProcess":
        d = json.loads(text)
        schema = Schema.from_json(json.dumps(d["initial_schema"]))
        b = TransformProcess.builder(schema)
        for spec in d["steps"]:
            kind = spec["kind"]
            if kind == "derive_column":
                # the custom fn is not serializable (reference parity: custom
                # transforms round-trip by class name only) — fail loudly
                # instead of rebuilding a pipeline that crashes at execute.
                raise ValueError(
                    "cannot deserialize a derive_column step: its fn is not "
                    "JSON-serializable; rebuild the pipeline in code"
                )
            args = {k: v for k, v in spec.items() if k != "kind"}
            if not hasattr(b, kind):
                raise ValueError(f"unknown transform step {kind!r}")
            if kind in ("remove_columns", "keep_columns", "reorder_columns"):
                # these builders are declared (*names); their spec
                # serializes {"names": [...]} — unpack positionally
                getattr(b, kind)(*args["names"])
            else:
                getattr(b, kind)(**args)
        return b.build()

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)

    # ------------------------------------------------------------------
    class Builder:
        def __init__(self, schema: Schema):
            self._schema = schema
            self._steps: List[_Step] = []

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, self._steps)

        def _add(self, name, schema_fn, records_fn, spec):
            self._steps.append(_Step(name, schema_fn, records_fn, spec))
            self._running_schema = schema_fn(self._current_schema())
            return self

        def _require_sequence_mode(self, kind: str):
            if not any(
                st.spec.get("kind") == "convert_to_sequence"
                for st in self._steps
            ):
                raise ValueError(
                    f"{kind} operates on sequences; add "
                    "convert_to_sequence(key, sort) earlier in the pipeline"
                )

        # --- column selection ---------------------------------------
        def remove_columns(self, *names: str):
            names_l = list(names) if not (len(names) == 1 and isinstance(names[0], list)) else list(names[0])

            def schema_fn(s: Schema) -> Schema:
                for n in names_l:
                    s.index_of(n)
                return Schema([c for c in s.columns if c.name not in names_l])

            def records_fn(s: Schema, recs: Records) -> Records:
                keep = [i for i, c in enumerate(s.columns) if c.name not in names_l]
                return [[r[i] for i in keep] for r in recs]

            return self._add("remove_columns", schema_fn, records_fn, {"kind": "remove_columns", "names": names_l})

        def keep_columns(self, *names: str):
            names_l = list(names) if not (len(names) == 1 and isinstance(names[0], list)) else list(names[0])

            def schema_fn(s: Schema) -> Schema:
                return Schema([s.meta(n) for n in names_l])

            def records_fn(s: Schema, recs: Records) -> Records:
                idx = [s.index_of(n) for n in names_l]
                return [[r[i] for i in idx] for r in recs]

            return self._add("keep_columns", schema_fn, records_fn, {"kind": "keep_columns", "names": names_l})

        def rename_column(self, old: str, new: str):
            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(old)
                cols = list(s.columns)
                cols[i] = ColumnMeta(new, cols[i].type, cols[i].categories)
                return Schema(cols)

            return self._add(
                "rename_column", schema_fn, lambda s, recs: recs,
                {"kind": "rename_column", "old": old, "new": new},
            )

        def reorder_columns(self, *names: str):
            names_l = list(names) if not (len(names) == 1 and isinstance(names[0], list)) else list(names[0])

            def schema_fn(s: Schema) -> Schema:
                rest = [c.name for c in s.columns if c.name not in names_l]
                return Schema([s.meta(n) for n in names_l + rest])

            def records_fn(s: Schema, recs: Records) -> Records:
                rest = [c.name for c in s.columns if c.name not in names_l]
                idx = [s.index_of(n) for n in names_l + rest]
                return [[r[i] for i in idx] for r in recs]

            return self._add("reorder_columns", schema_fn, records_fn, {"kind": "reorder_columns", "names": names_l})

        # --- categorical --------------------------------------------
        def string_to_categorical(self, name: str, categories: Sequence[str]):
            cats = tuple(categories)

            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.CATEGORICAL, cats)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    if r[i] not in cats:
                        raise ValueError(f"value {r[i]!r} not in categories {cats} for column {name!r}")
                return recs

            return self._add(
                "string_to_categorical", schema_fn, records_fn,
                {"kind": "string_to_categorical", "name": name, "categories": list(cats)},
            )

        def categorical_to_integer(self, name: str):
            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                if s.columns[i].type != ColumnType.CATEGORICAL:
                    raise ValueError(f"{name!r} is not categorical")
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.INTEGER)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                lookup = {c: j for j, c in enumerate(s.columns[i].categories)}
                for r in recs:
                    r[i] = lookup[r[i]]
                return recs

            return self._add(
                "categorical_to_integer", schema_fn, records_fn,
                {"kind": "categorical_to_integer", "name": name},
            )

        def categorical_to_one_hot(self, name: str):
            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                if s.columns[i].type != ColumnType.CATEGORICAL:
                    raise ValueError(f"{name!r} is not categorical")
                cols = list(s.columns)
                onehot = [ColumnMeta(f"{name}[{c}]", ColumnType.INTEGER) for c in s.columns[i].categories]
                return Schema(cols[:i] + onehot + cols[i + 1:])

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                cats = s.columns[i].categories
                lookup = {c: j for j, c in enumerate(cats)}
                out = []
                for r in recs:
                    vec = [0] * len(cats)
                    vec[lookup[r[i]]] = 1
                    out.append(r[:i] + vec + r[i + 1:])
                return out

            return self._add(
                "categorical_to_one_hot", schema_fn, records_fn,
                {"kind": "categorical_to_one_hot", "name": name},
            )

        # --- math ----------------------------------------------------
        def double_math_op(self, name: str, op: str, scalar: float):
            ops = {
                "add": lambda v: v + scalar,
                "subtract": lambda v: v - scalar,
                "multiply": lambda v: v * scalar,
                "divide": lambda v: v / scalar,
                "power": lambda v: v ** scalar,
            }
            if op not in ops:
                raise ValueError(f"unknown op {op!r}; have {sorted(ops)}")

            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.DOUBLE)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                f = ops[op]
                for r in recs:
                    r[i] = f(float(r[i]))
                return recs

            return self._add(
                "double_math_op", schema_fn, records_fn,
                {"kind": "double_math_op", "name": name, "op": op, "scalar": scalar},
            )

        def normalize_min_max(self, name: str, min_val: float, max_val: float):
            """Scale [min_val, max_val] → [0, 1] (reference Normalize.MinMax)."""
            span = max_val - min_val

            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.DOUBLE)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    r[i] = (float(r[i]) - min_val) / span
                return recs

            return self._add(
                "normalize_min_max", schema_fn, records_fn,
                {"kind": "normalize_min_max", "name": name, "min_val": min_val, "max_val": max_val},
            )

        def normalize_standardize(self, name: str, mean: float, std: float):
            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.DOUBLE)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    r[i] = (float(r[i]) - mean) / std
                return recs

            return self._add(
                "normalize_standardize", schema_fn, records_fn,
                {"kind": "normalize_standardize", "name": name, "mean": mean, "std": std},
            )

        # --- filter / replace ---------------------------------------
        def filter_rows(self, name: str, condition: str, value):
            """Drop rows where the condition HOLDS (reference FilterInvalidValues/
            ConditionFilter semantics: filter = remove matching)."""
            conds = {
                "lt": lambda v: v < value,
                "lte": lambda v: v <= value,
                "gt": lambda v: v > value,
                "gte": lambda v: v >= value,
                "eq": lambda v: v == value,
                "neq": lambda v: v != value,
            }
            if condition not in conds:
                raise ValueError(f"unknown condition {condition!r}")

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                f = conds[condition]
                return [r for r in recs if not f(r[i])]

            return self._add(
                "filter_rows", lambda s: s, records_fn,
                {"kind": "filter_rows", "name": name, "condition": condition, "value": value},
            )

        def replace_where(self, name: str, condition: str, value, replacement):
            conds = {
                "lt": lambda v: v < value,
                "lte": lambda v: v <= value,
                "gt": lambda v: v > value,
                "gte": lambda v: v >= value,
                "eq": lambda v: v == value,
                "neq": lambda v: v != value,
            }
            if condition not in conds:
                raise ValueError(f"unknown condition {condition!r}; have {sorted(conds)}")

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                f = conds[condition]
                for r in recs:
                    if f(r[i]):
                        r[i] = replacement
                return recs

            return self._add(
                "replace_where", lambda s: s, records_fn,
                {"kind": "replace_where", "name": name, "condition": condition,
                 "value": value, "replacement": replacement},
            )

        # --- derived columns ----------------------------------------
        def add_constant_column(self, name: str, col_type: str, value):
            def schema_fn(s: Schema) -> Schema:
                return Schema(list(s.columns) + [ColumnMeta(name, ColumnType(col_type))])

            def records_fn(s: Schema, recs: Records) -> Records:
                for r in recs:
                    r.append(value)
                return recs

            return self._add(
                "add_constant_column", schema_fn, records_fn,
                {"kind": "add_constant_column", "name": name, "col_type": col_type, "value": value},
            )

        # --- string transforms (the reference's StringMap / ReplaceString
        # / ChangeCase / Append / ReplaceEmpty / Concatenate family) ------
        def _require_string(self, name: str):
            m = self._current_schema().meta(name)
            if m.type != ColumnType.STRING:
                raise ValueError(
                    f"column {name!r} is {m.type}, expected STRING"
                )

        def _current_schema(self) -> Schema:
            # running schema, updated incrementally per _add — replaying
            # every prior schema_fn here would make builds O(steps^2)
            if not hasattr(self, "_running_schema"):
                self._running_schema = self._schema
            return self._running_schema

        def _string_op(self, kind: str, name: str, fn, spec_extra: dict):
            self._require_string(name)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    r[i] = fn(str(r[i]))
                return recs

            return self._add(kind, lambda s: s, records_fn,
                             {"kind": kind, "name": name, **spec_extra})

        def string_map(self, name: str, mapping: dict):
            """Exact-match value replacement (StringMapTransform role)."""
            m = dict(mapping)
            return self._string_op(
                "string_map", name, lambda v: m.get(v, v),
                {"mapping": m},
            )

        def replace_string(self, name: str, regex: str, replacement: str):
            """Regex substitution (ReplaceStringTransform role)."""
            import re as _re

            pat = _re.compile(regex)
            return self._string_op(
                "replace_string", name,
                lambda v: pat.sub(replacement, v),
                {"regex": regex, "replacement": replacement},
            )

        def change_case(self, name: str, mode: str = "lower"):
            if mode not in ("lower", "upper"):
                raise ValueError(f"change_case mode must be lower/upper, got {mode!r}")
            return self._string_op(
                "change_case", name,
                (str.lower if mode == "lower" else str.upper),
                {"mode": mode},
            )

        def append_string(self, name: str, suffix: str):
            return self._string_op(
                "append_string", name, lambda v: v + suffix,
                {"suffix": suffix},
            )

        def prepend_string(self, name: str, prefix: str):
            return self._string_op(
                "prepend_string", name, lambda v: prefix + v,
                {"prefix": prefix},
            )

        def trim_string(self, name: str):
            return self._string_op("trim_string", name, str.strip, {})

        def replace_empty(self, name: str, value: str):
            return self._string_op(
                "replace_empty", name,
                lambda v: value if v == "" else v,
                {"value": value},
            )

        def concat_strings(self, new_name: str, sources: Sequence[str],
                           delimiter: str = ""):
            """New STRING column joining existing string columns
            (ConcatenateStringColumns role)."""
            srcs = list(sources)
            cur = self._current_schema()
            for n in srcs:
                m = cur.meta(n)
                if m.type != ColumnType.STRING:
                    raise ValueError(
                        f"concat_strings source {n!r} is {m.type}, "
                        "expected STRING"
                    )

            def schema_fn(s: Schema) -> Schema:
                return Schema(
                    list(s.columns) + [ColumnMeta(new_name, ColumnType.STRING)]
                )

            def records_fn(s: Schema, recs: Records) -> Records:
                idx = [s.index_of(n) for n in srcs]
                for r in recs:
                    r.append(delimiter.join(str(r[i]) for i in idx))
                return recs

            return self._add(
                "concat_strings", schema_fn, records_fn,
                {"kind": "concat_strings", "new_name": new_name,
                 "sources": srcs, "delimiter": delimiter},
            )

        # --- time transforms (StringToTime / DeriveColumnsFromTime) -----
        def string_to_time(self, name: str, fmt: str):
            """Parse a STRING column into a TIME column of epoch MILLIS
            (StringToTimeTransform role).  fmt is strptime syntax; naive
            timestamps are taken as UTC, an offset in the format (%z) is
            honored."""
            import datetime as _dt

            self._require_string(name)

            def schema_fn(s: Schema) -> Schema:
                i = s.index_of(name)
                cols = list(s.columns)
                cols[i] = ColumnMeta(name, ColumnType.TIME)
                return Schema(cols)

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    t = _dt.datetime.strptime(str(r[i]), fmt)
                    if t.tzinfo is None:
                        t = t.replace(tzinfo=_dt.timezone.utc)
                    r[i] = int(t.timestamp() * 1000)
                return recs

            return self._add(
                "string_to_time", schema_fn, records_fn,
                {"kind": "string_to_time", "name": name, "fmt": fmt},
            )

        _TIME_FIELDS = ("year", "month", "day", "hour", "minute", "second",
                        "day_of_week")

        def derive_time_fields(self, name: str, fields: Sequence[str]):
            """From an epoch-millis LONG column, append INTEGER columns for
            the requested UTC fields (DeriveColumnsFromTimeTransform role)."""
            import datetime as _dt

            fields = list(fields)
            bad = [f for f in fields if f not in self._TIME_FIELDS]
            if bad:
                raise ValueError(
                    f"unknown time fields {bad}; options: {self._TIME_FIELDS}"
                )
            m = self._current_schema().meta(name)
            if m.type not in (ColumnType.TIME, ColumnType.LONG,
                              ColumnType.INTEGER):
                raise ValueError(
                    f"column {name!r} is {m.type}, expected TIME/LONG "
                    "epoch millis"
                )

            def schema_fn(s: Schema) -> Schema:
                return Schema(
                    list(s.columns)
                    + [ColumnMeta(f"{name}_{f}", ColumnType.INTEGER)
                       for f in fields]
                )

            def records_fn(s: Schema, recs: Records) -> Records:
                i = s.index_of(name)
                for r in recs:
                    t = _dt.datetime.fromtimestamp(
                        int(r[i]) / 1000.0, tz=_dt.timezone.utc
                    )
                    for f in fields:
                        if f == "day_of_week":
                            r.append(t.weekday())
                        else:
                            r.append(getattr(t, f))
                return recs

            return self._add(
                "derive_time_fields", schema_fn, records_fn,
                {"kind": "derive_time_fields", "name": name,
                 "fields": fields},
            )

        def derive_column(self, name: str, col_type: str, sources: Sequence[str],
                          fn: Optional[Callable] = None):
            """Custom derived column.  `fn(*source_values)`; not JSON round-trippable
            (reference parity: custom transforms serialize by class name only)."""
            srcs = list(sources)

            def schema_fn(s: Schema) -> Schema:
                for n in srcs:
                    s.index_of(n)
                return Schema(list(s.columns) + [ColumnMeta(name, ColumnType(col_type))])

            def records_fn(s: Schema, recs: Records) -> Records:
                idx = [s.index_of(n) for n in srcs]
                for r in recs:
                    r.append(fn(*[r[i] for i in idx]))
                return recs

            return self._add(
                "derive_column", schema_fn, records_fn,
                {"kind": "derive_column", "name": name, "col_type": col_type, "sources": srcs},
            )

        # --- sequence operations (the reference's convertToSequence /
        # offset / trim / moving-window sequence transforms) -----------
        def convert_to_sequence(self, key_column: str, sort_column: str):
            """Group rows by key, sort each group by sort_column: the
            record stream becomes List[sequence].  Subsequent column
            steps apply per step-row within each sequence; sequence
            steps below operate on whole sequences."""

            def schema_fn(s: Schema) -> Schema:
                s.index_of(key_column)
                s.index_of(sort_column)
                return s

            def records_fn(s: Schema, recs: Records) -> Records:
                ki, si = s.index_of(key_column), s.index_of(sort_column)
                groups: dict = {}
                order = []
                for r in recs:
                    k = r[ki]
                    if k not in groups:
                        groups[k] = []
                        order.append(k)
                    groups[k].append(r)
                return [
                    sorted(groups[k], key=lambda r: r[si]) for k in order
                ]

            return self._add(
                "convert_to_sequence", schema_fn, records_fn,
                {"kind": "convert_to_sequence", "key_column": key_column,
                 "sort_column": sort_column},
            )

        def offset_sequence(self, columns, offset: int):
            self._require_sequence_mode("offset_sequence")
            """Shift the named columns by `offset` steps WITHIN each
            sequence (positive = values move toward later steps — lag
            features; negative = lead).  Steps that lose a value are
            trimmed, so every emitted row is fully populated."""
            cols = list(columns) if not isinstance(columns, str) else [columns]

            def schema_fn(s: Schema) -> Schema:
                for c in cols:
                    s.index_of(c)
                return s

            def records_fn(s: Schema, seqs: Records) -> Records:
                idx = [s.index_of(c) for c in cols]
                out = []
                for seq in seqs:
                    n = len(seq)
                    k = abs(offset)
                    if n <= k:
                        continue
                    rows = []
                    if offset > 0:
                        # row t carries column value from t-offset
                        for t in range(k, n):
                            r = list(seq[t])
                            for i in idx:
                                r[i] = seq[t - k][i]
                            rows.append(r)
                    else:
                        for t in range(0, n - k):
                            r = list(seq[t])
                            for i in idx:
                                r[i] = seq[t + k][i]
                            rows.append(r)
                    out.append(rows)
                return out

            return self._add(
                "offset_sequence", schema_fn, records_fn,
                {"kind": "offset_sequence", "columns": cols,
                 "offset": offset},
            )

        def trim_sequence(self, num_steps: int, from_start: bool = True):
            self._require_sequence_mode("trim_sequence")
            """Drop num_steps rows from the start (or end) of every
            sequence; sequences that would empty are removed."""

            def records_fn(s: Schema, seqs: Records) -> Records:
                out = []
                for seq in seqs:
                    t = seq[num_steps:] if from_start else (
                        seq[:-num_steps] if num_steps else seq
                    )
                    if t:
                        out.append(t)
                return out

            return self._add(
                "trim_sequence", lambda s: s, records_fn,
                {"kind": "trim_sequence", "num_steps": num_steps,
                 "from_start": from_start},
            )

        def sequence_moving_window_reduce(self, column: str, window: int,
                                          op: str = "mean"):
            self._require_sequence_mode("sequence_moving_window_reduce")
            if int(window) < 1:
                raise ValueError(f"window must be >= 1, got {window}")
            """New column <column>_<op>_<window>: the op over the
            TRAILING window ending at each step (fewer at the head —
            the reference's SequenceMovingWindowReduce edge behavior)."""
            ops = {
                "mean": lambda v: sum(v) / len(v),
                "sum": sum,
                "min": min,
                "max": max,
            }
            if op not in ops:
                raise ValueError(
                    f"unknown moving-window op {op!r}; have {sorted(ops)}"
                )
            new_name = f"{column}_{op}_{window}"

            def schema_fn(s: Schema) -> Schema:
                s.index_of(column)
                return Schema(
                    list(s.columns)
                    + [ColumnMeta(new_name, ColumnType.DOUBLE)]
                )

            def records_fn(s: Schema, seqs: Records) -> Records:
                ci = s.index_of(column)
                out = []
                for seq in seqs:
                    rows = []
                    for t, r in enumerate(seq):
                        lo = max(0, t - window + 1)
                        vals = [float(seq[u][ci]) for u in range(lo, t + 1)]
                        rows.append(list(r) + [ops[op](vals)])
                    out.append(rows)
                return out

            return self._add(
                "sequence_moving_window_reduce", schema_fn, records_fn,
                {"kind": "sequence_moving_window_reduce", "column": column,
                 "window": window, "op": op},
            )
