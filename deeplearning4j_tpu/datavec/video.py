"""Video sequences — the `datavec-data-codec` VideoRecordReader role.

The reference decodes video through FFmpeg/JavaCV; neither ships in this
image, so this reader implements the subset that needs no external codec:
**MJPEG-in-AVI** (each frame is an independent JPEG — the format cheap
cameras and OpenCV's default writer emit).  The RIFF/AVI container is
parsed with the stdlib; JPEG frames decode through PIL (already a
dependency of ImageRecordReader).  Any other codec raises with re-encode
advice.

Record layout per video: `[frames (T,H,W,C) float32, label_index int]`
— channels-last like ImageRecordReader (NHWC is the TPU conv layout; the
reference emits NCHW for cuDNN).  A `write_mjpeg_avi` helper produces
standard AVI files (playable by FFmpeg-class tools) for tests/pipelines.
"""

from __future__ import annotations

import io
import os
import random
import struct
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader


def _iter_chunks(data: bytes, offset: int, end: int):
    """Depth-first walk of RIFF chunks: yields (fourcc, payload_bytes)."""
    while offset + 8 <= end:
        fourcc = data[offset : offset + 4]
        size = struct.unpack_from("<I", data, offset + 4)[0]
        payload = offset + 8
        if fourcc in (b"RIFF", b"LIST"):
            yield from _iter_chunks(data, payload + 4, min(payload + size, len(data)))
        else:
            yield fourcc, data[payload : payload + size]
        offset = payload + size + (size & 1)   # chunks are word-aligned


def read_avi_frames(path, height: int, width: int, channels: int = 3,
                    max_frames: Optional[int] = None) -> np.ndarray:
    """Decode an AVI's video frames to (T, H, W, C) float32.

    '00dc'/'00db' stream chunks whose payload starts with a JPEG SOI
    marker decode through PIL; anything else raises with the codec advice
    the old gate gave."""
    from PIL import Image

    data = Path(path).read_bytes()
    if data[:4] != b"RIFF" or data[8:12] != b"AVI ":
        raise ValueError(f"{path}: not an AVI (RIFF) file")
    frames = []
    for fourcc, payload in _iter_chunks(data, 0, len(data)):
        # stream 00 only: a multi-stream AVI (main + thumbnail mux) must
        # not interleave unrelated streams into one clip
        if fourcc[:2] != b"00" or fourcc[2:4] not in (b"dc", b"db") or not payload:
            continue
        if payload[:2] != b"\xff\xd8":      # JPEG SOI
            raise NotImplementedError(
                f"{path}: non-MJPEG video stream (chunk {fourcc!r}); only "
                "MJPEG-in-AVI decodes without FFmpeg-class codecs — "
                "re-encode with MJPEG or extract frames offline and use "
                "ImageRecordReader"
            )
        img = Image.open(io.BytesIO(payload))
        img = img.convert("L" if channels == 1 else "RGB")
        img = img.resize((width, height))
        arr = np.asarray(img, np.float32)
        if channels == 1:
            arr = arr[..., None]
        frames.append(arr)
        if max_frames and len(frames) >= max_frames:
            break
    if not frames:
        raise ValueError(f"{path}: no video frames found")
    return np.stack(frames)


class VideoRecordReader(RecordReader):
    """Directory-tree MJPEG-AVI reader with parent-dir labels — mirrors
    ImageRecordReader's conventions, one record per VIDEO."""

    def __init__(self, height: int, width: int, channels: int = 3, *,
                 max_frames: Optional[int] = None,
                 shuffle_seed: Optional[int] = None,
                 label_generator=None):
        self.height, self.width, self.channels = height, width, channels
        self.max_frames = max_frames
        self._shuffle_seed = shuffle_seed
        self._label_of = label_generator or (lambda p: p.parent.name)
        self._files: List[Path] = []
        self.labels: List[str] = []

    _OTHER_VIDEO_EXTS = {".mp4", ".mkv", ".mov", ".webm", ".mpg", ".mpeg",
                         ".wmv", ".flv", ".m4v"}

    def initialize(self, root) -> "VideoRecordReader":
        root = Path(root)
        all_files = [p for p in root.rglob("*") if p.is_file()]
        self._files = sorted(
            p for p in all_files if p.suffix.lower() == ".avi"
        )
        if not self._files:
            others = [p for p in all_files
                      if p.suffix.lower() in self._OTHER_VIDEO_EXTS]
            if others:
                raise NotImplementedError(
                    f"{len(others)} non-AVI video file(s) under {root} "
                    f"(e.g. {others[0].name}): only MJPEG-in-AVI decodes "
                    "without FFmpeg-class codecs — re-encode to MJPEG AVI, "
                    "or extract frames offline and use ImageRecordReader"
                )
            raise FileNotFoundError(f"no .avi files under {root}")
        self.labels = sorted({self._label_of(p) for p in self._files})
        if self._shuffle_seed is not None:
            random.Random(self._shuffle_seed).shuffle(self._files)
        return self

    def __iter__(self):
        label_idx = {name: i for i, name in enumerate(self.labels)}
        for p in self._files:
            frames = read_avi_frames(
                p, self.height, self.width, self.channels,
                max_frames=self.max_frames,
            )
            yield [frames, label_idx[self._label_of(p)]]

    def num_videos(self) -> int:
        return len(self._files)


def write_mjpeg_avi(path, frames: np.ndarray, fps: int = 25,
                    quality: int = 90) -> None:
    """Write (T, H, W, C) uint8/float frames as a standard MJPEG AVI."""
    from PIL import Image

    frames = np.asarray(frames)
    if frames.dtype != np.uint8:
        frames = np.clip(frames, 0, 255).astype(np.uint8)
    T, H, W = frames.shape[:3]
    jpegs = []
    for f in frames:
        img = Image.fromarray(f[..., 0] if f.shape[-1] == 1 else f)
        buf = io.BytesIO()
        img.save(buf, "JPEG", quality=quality)
        jpegs.append(buf.getvalue())

    def chunk(fourcc: bytes, payload: bytes) -> bytes:
        # RIFF: declared size EXCLUDES the word-alignment pad byte
        return fourcc + struct.pack("<I", len(payload)) + payload + (
            b"\x00" if len(payload) & 1 else b""
        )

    def lst(kind: bytes, payload: bytes) -> bytes:
        return chunk(b"LIST", kind + payload)

    max_size = max(len(j) for j in jpegs)
    avih = struct.pack(
        "<14I", 1_000_000 // fps, max_size * fps, 0, 0x10, T, 0, 1,
        max_size, W, H, 0, 0, 0, 0,
    )
    # AVISTREAMHEADER is 56 bytes: ...dwSampleSize then rcFrame (4 WORDs)
    strh = b"vids" + b"MJPG" + struct.pack(
        "<IHHIIIIIIII4H", 0, 0, 0, 0, 1, fps, 0, T, max_size, 0xFFFFFFFF,
        0, 0, 0, W, H,
    )
    strf = struct.pack("<IiiHH4sIiiII", 40, W, H, 1, 24, b"MJPG",
                       W * H * 3, 0, 0, 0, 0)
    hdrl = lst(
        b"hdrl",
        chunk(b"avih", avih)
        + lst(b"strl", chunk(b"strh", strh) + chunk(b"strf", strf)),
    )
    # movi data + idx1 (offsets are relative to the 'movi' fourcc)
    frame_chunks = []
    idx_entries = []
    offset = 4                               # just past the 'movi' fourcc
    for j in jpegs:
        idx_entries.append(
            b"00dc" + struct.pack("<III", 0x10, offset, len(j))
        )
        c = chunk(b"00dc", j)
        frame_chunks.append(c)
        offset += len(c)
    movi = lst(b"movi", b"".join(frame_chunks))
    idx1 = chunk(b"idx1", b"".join(idx_entries))
    body = b"AVI " + hdrl + movi + idx1
    with open(path, "wb") as f:
        f.write(b"RIFF" + struct.pack("<I", len(body)) + body)
