"""Joins and group-by reductions over record streams.

Reference roles: `org.datavec.api.transform.join.Join` (Inner/LeftOuter/
RightOuter/FullOuter on key columns) and `org.datavec.api.transform.reduce.
Reducer` (group-by keys + per-column aggregation ops), executed by the
local/Spark executors (SURVEY.md §2.2 "DataVec" — previously a parity
gap).  The executor here is local and hash-based; the cluster tier's role
is played by the data-parallel input pipeline.
"""

from __future__ import annotations

import enum
import math
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema

Records = List[list]


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"


class Join:
    """Hash join on key columns.

    Output schema: key columns (typed from the left), then the left
    non-key columns, then the right non-key columns.  Missing sides in
    outer joins fill with None.
    """

    def __init__(self, join_type: JoinType | str, left_schema: Schema,
                 right_schema: Schema, *key_columns: str):
        self.join_type = JoinType(join_type)
        if not key_columns:
            raise ValueError("at least one key column required")
        for k in key_columns:
            if k not in left_schema.column_names():
                raise ValueError(f"key {k!r} not in left schema")
            if k not in right_schema.column_names():
                raise ValueError(f"key {k!r} not in right schema")
        self.keys = list(key_columns)
        self.left_schema = left_schema
        self.right_schema = right_schema
        self._l_key_idx = [left_schema.column_names().index(k) for k in self.keys]
        self._r_key_idx = [right_schema.column_names().index(k) for k in self.keys]
        self._l_rest = [
            i for i, c in enumerate(left_schema.columns)
            if c.name not in self.keys
        ]
        self._r_rest = [
            i for i, c in enumerate(right_schema.columns)
            if c.name not in self.keys
        ]

    def output_schema(self) -> Schema:
        cols = [self.left_schema.columns[i] for i in self._l_key_idx]
        cols += [self.left_schema.columns[i] for i in self._l_rest]
        cols += [self.right_schema.columns[i] for i in self._r_rest]
        return Schema(cols)

    def execute(self, left: Records, right: Records) -> Records:
        by_key: Dict[tuple, list] = {}
        for r in right:
            by_key.setdefault(
                tuple(r[i] for i in self._r_key_idx), []
            ).append(r)
        out: Records = []
        matched_right: set = set()
        for l in left:
            key = tuple(l[i] for i in self._l_key_idx)
            matches = by_key.get(key)
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(
                        list(key)
                        + [l[i] for i in self._l_rest]
                        + [r[i] for i in self._r_rest]
                    )
            elif self.join_type in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER):
                out.append(
                    list(key)
                    + [l[i] for i in self._l_rest]
                    + [None] * len(self._r_rest)
                )
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            for key, matches in by_key.items():
                if key in matched_right:
                    continue
                for r in matches:
                    out.append(
                        list(key)
                        + [None] * len(self._l_rest)
                        + [r[i] for i in self._r_rest]
                    )
        return out


class ReduceOp(enum.Enum):
    SUM = "sum"
    MEAN = "mean"
    MIN = "min"
    MAX = "max"
    COUNT = "count"
    STDEV = "stdev"
    FIRST = "first"
    LAST = "last"
    RANGE = "range"          # max - min


_NUMERIC_OUT = {
    ReduceOp.SUM: ColumnType.DOUBLE,
    ReduceOp.MEAN: ColumnType.DOUBLE,
    ReduceOp.MIN: ColumnType.DOUBLE,
    ReduceOp.MAX: ColumnType.DOUBLE,
    ReduceOp.COUNT: ColumnType.LONG,
    ReduceOp.STDEV: ColumnType.DOUBLE,
    ReduceOp.RANGE: ColumnType.DOUBLE,
}


def _reduce_values(op: ReduceOp, values: list):
    if op is ReduceOp.COUNT:
        return len(values)
    if op is ReduceOp.FIRST:
        return values[0] if values else None
    if op is ReduceOp.LAST:
        return values[-1] if values else None
    nums = [float(v) for v in values if v is not None]
    if not nums:
        return None
    if op is ReduceOp.SUM:
        return sum(nums)
    if op is ReduceOp.MEAN:
        return sum(nums) / len(nums)
    if op is ReduceOp.MIN:
        return min(nums)
    if op is ReduceOp.MAX:
        return max(nums)
    if op is ReduceOp.RANGE:
        return max(nums) - min(nums)
    if op is ReduceOp.STDEV:
        m = sum(nums) / len(nums)
        if len(nums) < 2:
            return 0.0
        return math.sqrt(sum((v - m) ** 2 for v in nums) / (len(nums) - 1))
    raise ValueError(f"unhandled op {op}")


class Reducer:
    """Group-by-keys aggregation with a per-column op map.

        reducer = (Reducer.builder(schema, "city")
                   .sum("sales").mean("price").count("id").build())
        out = reducer.execute(records)   # one record per key group

    Output schema: keys, then aggregated columns named "<op>(<col>)".
    """

    def __init__(self, schema: Schema, keys: Sequence[str],
                 ops: Sequence[tuple]):
        self.schema = schema
        self.keys = list(keys)
        for k in self.keys:
            if k not in schema.column_names():
                raise ValueError(f"key {k!r} not in schema")
        self.ops = [(ReduceOp(op), col) for op, col in ops]
        names = schema.column_names()
        for op, col in self.ops:
            if col not in names:
                raise ValueError(f"column {col!r} not in schema")
            meta = schema.columns[names.index(col)]
            if op in (ReduceOp.SUM, ReduceOp.MEAN, ReduceOp.MIN, ReduceOp.MAX,
                      ReduceOp.STDEV, ReduceOp.RANGE) and not meta.is_numeric():
                raise ValueError(
                    f"{op.value}({col}) needs a numeric column, got "
                    f"{meta.type.value}"
                )
        self._key_idx = [names.index(k) for k in self.keys]
        self._op_idx = [(op, names.index(col)) for op, col in self.ops]

    @staticmethod
    def builder(schema: Schema, *keys: str) -> "Reducer.Builder":
        return Reducer.Builder(schema, keys)

    class Builder:
        def __init__(self, schema: Schema, keys: Sequence[str]):
            self._schema = schema
            self._keys = list(keys)
            self._ops: List[tuple] = []

        def _op(self, op: ReduceOp, *cols: str) -> "Reducer.Builder":
            for c in cols:
                self._ops.append((op, c))
            return self

        def sum(self, *cols):
            return self._op(ReduceOp.SUM, *cols)

        def mean(self, *cols):
            return self._op(ReduceOp.MEAN, *cols)

        def min(self, *cols):
            return self._op(ReduceOp.MIN, *cols)

        def max(self, *cols):
            return self._op(ReduceOp.MAX, *cols)

        def count(self, *cols):
            return self._op(ReduceOp.COUNT, *cols)

        def stdev(self, *cols):
            return self._op(ReduceOp.STDEV, *cols)

        def first(self, *cols):
            return self._op(ReduceOp.FIRST, *cols)

        def last(self, *cols):
            return self._op(ReduceOp.LAST, *cols)

        def range(self, *cols):
            return self._op(ReduceOp.RANGE, *cols)

        def build(self) -> "Reducer":
            return Reducer(self._schema, self._keys, self._ops)

    def output_schema(self) -> Schema:
        names = self.schema.column_names()
        cols = [self.schema.columns[i] for i in self._key_idx]
        for op, idx in self._op_idx:
            src = self.schema.columns[idx]
            if op in (ReduceOp.FIRST, ReduceOp.LAST):
                out_type = src.type
            else:
                out_type = _NUMERIC_OUT[op]
            cols.append(
                ColumnMeta(f"{op.value}({src.name})", out_type,
                           src.categories if op in (ReduceOp.FIRST,
                                                    ReduceOp.LAST) else None)
            )
        return Schema(cols)

    def execute(self, records: Records) -> Records:
        groups: Dict[tuple, list] = {}
        order: List[tuple] = []
        for r in records:
            key = tuple(r[i] for i in self._key_idx)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)
        out: Records = []
        for key in order:
            rows = groups[key]
            rec = list(key)
            for op, idx in self._op_idx:
                rec.append(_reduce_values(op, [r[idx] for r in rows]))
            out.append(rec)
        return out
