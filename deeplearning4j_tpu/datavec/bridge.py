"""RecordReader → DataSetIterator bridge.

Role parity: `org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator`
(SURVEY.md §2.2 "Dataset iterators") — consumes a RecordReader, splits each
record into features / label, one-hots classification labels, and emits
`DataSet` minibatches.  Fixed batch shapes (final short batch padded-or-
dropped by choice) keep the compiled TPU step from recompiling.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader


class RecordReaderDataSetIterator(DataSetIterator):
    """Batches records from a RecordReader.

    Classification: `label_index` selects the label column, one-hotted to
    `num_classes` (reference constructor `(reader, batch, labelIdx, numClasses)`).
    Regression: `regression=True` keeps the label columns raw; `label_index`
    .. `label_index_to` select a contiguous label span (inclusive), matching
    the reference's regression constructor.
    Image records (`[ndarray, label]`): the feature cell is used as-is.
    """

    def __init__(
        self,
        reader: RecordReader,
        batch_size: int,
        label_index: Optional[int] = None,
        num_classes: Optional[int] = None,
        *,
        regression: bool = False,
        label_index_to: Optional[int] = None,
        drop_last: bool = False,
    ):
        if not regression and label_index is not None and num_classes is None:
            raise ValueError("classification mode requires num_classes")
        self._reader = reader
        self._batch = int(batch_size)
        self._label_index = label_index
        self._label_index_to = label_index_to if label_index_to is not None else label_index
        self._num_classes = num_classes
        self._regression = regression
        self._drop_last = drop_last

    @property
    def batch_size(self) -> int:
        return self._batch

    def reset(self) -> None:
        self._reader.reset()

    def _split(self, record: list):
        if self._label_index is None:
            return record, None
        if (
            len(record) == 2
            and isinstance(record[0], np.ndarray)
            and self._label_index == 1
        ):
            # image record: [tensor, label]
            return record[0], record[1]
        lo, hi = self._label_index, self._label_index_to
        label = record[lo : hi + 1]
        feats = record[:lo] + record[hi + 1 :]
        return feats, label[0] if len(label) == 1 else label

    def _emit(self, feats: list, labels: list) -> DataSet:
        f = np.asarray(feats)
        if f.dtype != np.uint8:
            # uint8 passes through untouched: it is the WIRE format for
            # the device-cast image path (4x fewer host->device bytes;
            # models cast to the compute dtype inside the jitted step)
            f = f.astype(np.float32, copy=False)
        if not labels or labels[0] is None:
            return DataSet(f, np.zeros((len(feats), 0), np.float32))
        if self._regression:
            y = np.asarray(labels, dtype=np.float32)
            if y.ndim == 1:
                y = y[:, None]
        else:
            idx = np.asarray(labels, dtype=np.int64).reshape(-1)
            if (idx < 0).any() or (idx >= self._num_classes).any():
                raise ValueError(
                    f"label out of range [0, {self._num_classes}): {idx.min()}..{idx.max()}"
                )
            y = np.eye(self._num_classes, dtype=np.float32)[idx]
        return DataSet(f, y)

    def __iter__(self) -> Iterator[DataSet]:
        feats, labels = [], []
        for record in self._reader:
            x, y = self._split(list(record))
            feats.append(x)
            labels.append(y)
            if len(feats) == self._batch:
                yield self._emit(feats, labels)
                feats, labels = [], []
        if feats and not self._drop_last:
            yield self._emit(feats, labels)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records -> bucketed (B, T, F) batches — the reference's
    `SequenceRecordReaderDataSetIterator` with recompile hygiene.

    Consumes a sequence reader (e.g. `CSVSequenceRecordReader`: one
    sequence = a list of timestep records).  Each batch's time axis is
    padded to the longest member rounded UP to the bucket quantum
    (`flags.sequence_bucket_size` unless `bucket_size` overrides), and
    sequences are grouped into same-bucket batches, so a ragged corpus
    compiles at most ceil(max_len/quantum) step programs instead of one
    per distinct length.  `features_mask` (B, T) marks real timesteps.

    Classification (`label_index` + `num_classes`): per-timestep labels
    one-hot to (B, T, C) with `labels_mask` = features_mask.
    Regression keeps label columns raw as (B, T, L).
    `label_index=None` emits label-free batches (sequence pretraining).
    Tail batches of a bucket keep the full batch-size shape with padded
    examples masked out (mask rows all-zero) — batch shape stays static.
    """

    def __init__(
        self,
        reader,
        batch_size: int,
        label_index: Optional[int] = None,
        num_classes: Optional[int] = None,
        *,
        regression: bool = False,
        bucket_size: Optional[int] = None,
    ):
        if not regression and label_index is not None and num_classes is None:
            raise ValueError("classification mode requires num_classes")
        self._reader = reader
        self._batch = int(batch_size)
        self._label_index = label_index
        self._num_classes = num_classes
        self._regression = regression
        self._bucket = bucket_size

    @property
    def batch_size(self) -> int:
        return self._batch

    def reset(self) -> None:
        if hasattr(self._reader, "reset"):
            self._reader.reset()

    def _split_seq(self, seq: list):
        """One ragged sequence -> ((T, F) features, (T, L) labels-or-None)."""
        feats, labels = [], []
        for record in seq:
            record = list(record)
            if self._label_index is None:
                feats.append(record)
                continue
            lo = self._label_index
            labels.append(record[lo])
            feats.append(record[:lo] + record[lo + 1:])
        f = np.asarray(feats, np.float32)
        if self._label_index is None:
            return f, None
        if self._regression:
            y = np.asarray(labels, np.float32)
            if y.ndim == 1:
                y = y[:, None]
            return f, y
        idx = np.asarray(labels, np.int64)
        if (idx < 0).any() or (idx >= self._num_classes).any():
            raise ValueError(
                f"label out of range [0, {self._num_classes}): "
                f"{idx.min()}..{idx.max()}"
            )
        return f, np.eye(self._num_classes, dtype=np.float32)[idx]

    def _emit(self, seqs: list, bucket_len: int) -> DataSet:
        bs = self._batch
        n_feat = seqs[0][0].shape[1]
        f = np.zeros((bs, bucket_len, n_feat), np.float32)
        fmask = np.zeros((bs, bucket_len), np.float32)
        has_labels = seqs[0][1] is not None
        y = lmask = None
        if has_labels:
            n_lab = seqs[0][1].shape[1]
            y = np.zeros((bs, bucket_len, n_lab), np.float32)
            lmask = np.zeros((bs, bucket_len), np.float32)
        for j, (sf, sy) in enumerate(seqs):
            t = sf.shape[0]
            f[j, :t] = sf
            fmask[j, :t] = 1.0
            if has_labels:
                y[j, :t] = sy
                lmask[j, :t] = 1.0
        if not has_labels:
            y = np.zeros((bs, 0), np.float32)
        return DataSet(f, y, features_mask=fmask, labels_mask=lmask)

    def __iter__(self) -> Iterator[DataSet]:
        from deeplearning4j_tpu.runtime.flags import bucket_length

        pending: dict[int, list] = {}
        for seq_i, seq in enumerate(self._reader):
            seq = list(seq)
            if not seq:
                # an empty sequence file is an upstream ETL artifact;
                # name it here rather than dying in batch assembly with
                # a shape error that points nowhere
                raise ValueError(
                    f"sequence {seq_i} has zero timesteps; drop empty "
                    "sequences before the iterator"
                )
            sf, sy = self._split_seq(seq)
            L = bucket_length(sf.shape[0], self._bucket)
            bucket = pending.setdefault(L, [])
            bucket.append((sf, sy))
            if len(bucket) == self._batch:
                yield self._emit(bucket, L)
                pending[L] = []
        for L in sorted(pending):
            if pending[L]:
                yield self._emit(pending[L], L)
