"""Record readers — the `org.datavec.api.records.reader.RecordReader` role.

A record is a plain Python list of values (the reference's `List<Writable>`;
Writable boxing is a JVM artifact, not a capability).  Readers are iterables
with `reset()`, matching the reference SPI's `hasNext/next/reset` loop
(SURVEY.md §2.2 "DataVec (ETL)").

`ImageRecordReader` mirrors `org.datavec.image.recordreader.ImageRecordReader`:
walks a directory tree, labels from the parent directory name
(ParentPathLabelGenerator behavior), decodes via PIL instead of JavaCV,
emits HWC float arrays — channels-last, the TPU-friendly conv layout.
"""

from __future__ import annotations

import csv
import io
import os
import random
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np


class RecordReader:
    """Iterable-with-reset SPI.

    The stepwise `has_next()`/`next_record()` pair shares one lazily-created
    iterator plus a one-record peek buffer; `reset()` discards both so the
    next step starts a fresh pass.
    """

    _iter: Optional[Iterator[list]] = None
    _peek: Optional[list] = None

    def __iter__(self) -> Iterator[list]:
        raise NotImplementedError

    def reset(self) -> None:
        self._iter = None
        self._peek = None

    def next_record(self):
        """Convenience single-step API (reference `next()`)."""
        if self._peek is not None:
            rec, self._peek = self._peek, None
            return rec
        if self._iter is None:
            self._iter = iter(self)
        return next(self._iter)

    def has_next(self) -> bool:
        if self._peek is not None:
            return True
        if self._iter is None:
            self._iter = iter(self)
        try:
            self._peek = next(self._iter)
        except StopIteration:
            return False
        return True


class CollectionRecordReader(RecordReader):
    """In-memory records (reference `CollectionRecordReader`)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [list(r) for r in records]

    def __iter__(self):
        return iter([list(r) for r in self._records])


class LineRecordReader(RecordReader):
    """One record per line: `[line]` (reference `LineRecordReader`)."""

    def __init__(self, path: str | os.PathLike):
        self._path = Path(path)

    def __iter__(self):
        with open(self._path, "r") as f:
            for line in f:
                yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """CSV parsing with skip-lines and delimiter (reference `CSVRecordReader`).

    Values are type-sniffed per cell: int → float → string, matching how the
    reference's Writables come out of CSVRecordReader + downstream conversion.
    """

    def __init__(
        self,
        path: str | os.PathLike | None = None,
        skip_lines: int = 0,
        delimiter: str = ",",
        *,
        text: str | None = None,
    ):
        if (path is None) == (text is None):
            raise ValueError("exactly one of path/text required")
        self._path = Path(path) if path is not None else None
        self._text = text
        self._skip = skip_lines
        self._delim = delimiter

    @staticmethod
    def _convert(cell: str):
        cell = cell.strip()
        try:
            return int(cell)
        except ValueError:
            pass
        try:
            return float(cell)
        except ValueError:
            pass
        return cell

    def __iter__(self):
        if self._path is not None:
            f = open(self._path, "r", newline="")
        else:
            f = io.StringIO(self._text)
        try:
            reader = csv.reader(f, delimiter=self._delim)
            for i, row in enumerate(reader):
                if i < self._skip or not row:
                    continue
                yield [self._convert(c) for c in row]
        finally:
            f.close()


_IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif", ".npy"}


class ImageRecordReader(RecordReader):
    """Directory-tree image reader with parent-dir labels.

    Record layout: `[image(H,W,C) float32 ndarray, label_index int]` —
    channels-last (NHWC batches downstream; XLA:TPU's preferred conv layout),
    where the reference emits NCHW for cuDNN.  `.npy` files are read directly
    (golden-fixture path); everything else decodes through PIL.
    """

    def __init__(
        self,
        height: int,
        width: int,
        channels: int = 3,
        *,
        shuffle_seed: Optional[int] = None,
        label_generator=None,
        path_filter=None,
        dtype="float32",
    ):
        """label_generator: Path -> label string (default: parent dir —
        the ParentPathLabelGenerator behavior; see
        pattern_label_generator for the filename-pattern variant).
        path_filter: list[Path] -> list[Path] applied before shuffling
        (random_path_filter / balanced_path_filter roles).
        dtype: 'float32' (default) or 'uint8' — uint8 keeps decoded
        pixels as bytes end-to-end so batches cross the host->device
        link at 1/4 the size; models cast to the compute dtype on
        device (see models/_cast.entry_cast)."""
        self.height, self.width, self.channels = height, width, channels
        self.dtype = np.dtype(dtype)
        if self.dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
            raise ValueError(
                f"ImageRecordReader dtype must be float32 or uint8, "
                f"got {dtype}")
        self._shuffle_seed = shuffle_seed
        self._label_of = label_generator or (lambda p: p.parent.name)
        self._path_filter = path_filter
        self._files: List[Path] = []
        self.labels: List[str] = []

    def initialize(self, root: str | os.PathLike) -> "ImageRecordReader":
        root = Path(root)
        self._files = sorted(
            p for p in root.rglob("*") if p.suffix.lower() in _IMAGE_EXTS and p.is_file()
        )
        if not self._files:
            raise FileNotFoundError(f"no images under {root}")
        if self._path_filter is not None:
            self._files = list(self._path_filter(self._files))
            if not self._files:
                raise FileNotFoundError("path_filter removed every image")
        self.labels = sorted({self._label_of(p) for p in self._files})
        if self._shuffle_seed is not None:
            random.Random(self._shuffle_seed).shuffle(self._files)
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def _decode(self, path: Path) -> np.ndarray:
        if path.suffix.lower() == ".npy":
            img = np.load(path)
            if self.dtype == np.uint8 and img.dtype != np.uint8:
                # clamp-round like the native path — a bare astype would
                # truncate 254.9 -> 254 and WRAP negatives to 255
                img = np.clip(np.rint(img), 0, 255)
            img = img.astype(self.dtype)
            if img.ndim == 2:
                img = img[:, :, None]
        else:
            from PIL import Image

            with Image.open(path) as im:
                im = im.convert("L" if self.channels == 1 else "RGB")
                im = im.resize((self.width, self.height))
                img = np.asarray(im, dtype=self.dtype)
                if img.ndim == 2:
                    img = img[:, :, None]
        if img.shape != (self.height, self.width, self.channels):
            # pad/crop npy fixtures that bypass PIL resizing
            out = np.zeros((self.height, self.width, self.channels),
                           self.dtype)
            h = min(self.height, img.shape[0])
            w = min(self.width, img.shape[1])
            c = min(self.channels, img.shape[2])
            out[:h, :w, :c] = img[:h, :w, :c]
            img = out
        return img

    _NATIVE_CHUNK = 64

    def __iter__(self):
        label_idx = {name: i for i, name in enumerate(self.labels)}
        native_jpeg = False
        try:
            from deeplearning4j_tpu.runtime import native

            native_jpeg = native.has_jpeg()
        except Exception:
            pass
        if not native_jpeg:
            for p in self._files:
                yield [self._decode(p), label_idx[self._label_of(p)]]
            return
        # native fast path: decode JPEG runs in threaded C batches (the
        # reference's JavaCV-native decode tier); other formats per-file
        for i in range(0, len(self._files), self._NATIVE_CHUNK):
            chunk = self._files[i:i + self._NATIVE_CHUNK]
            jpegs = [p for p in chunk if p.suffix.lower() in (".jpg", ".jpeg")]
            decoded = {}
            if jpegs:
                from deeplearning4j_tpu.runtime import native

                batch = native.jpeg_batch_decode(
                    jpegs, self.height, self.width, self.channels,
                    dtype=self.dtype,
                )
                decoded = {p: batch[j] for j, p in enumerate(jpegs)}
            for p in chunk:
                img = decoded.get(p)
                if img is None or not img.any():
                    # native decode zero-fills failures; re-decode through
                    # PIL so corrupt files RAISE like the fallback path
                    # does (an all-black legit image just takes the slow
                    # path and comes back black again)
                    img = self._decode(p)
                yield [img, label_idx[self._label_of(p)]]


def pattern_label_generator(delimiter: str = "_", position: int = 0):
    """Label from a filename segment (PatternPathLabelGenerator role):
    'cat_001.png' with delimiter '_' position 0 -> 'cat'."""

    def gen(p: Path) -> str:
        parts = p.stem.split(delimiter)
        if position >= len(parts):
            raise ValueError(
                f"{p.name!r} has no segment {position} splitting on "
                f"{delimiter!r}"
            )
        return parts[position]

    return gen


def random_path_filter(seed: int, max_paths: int):
    """Random subsample of at most max_paths files (RandomPathFilter)."""

    def filt(paths: List[Path]) -> List[Path]:
        paths = list(paths)
        if len(paths) <= max_paths:
            return paths
        return random.Random(seed).sample(paths, max_paths)

    return filt


def balanced_path_filter(seed: int, max_per_class: int, label_generator=None):
    """At most max_per_class files per label, randomly chosen
    (BalancedPathFilter): guards against class imbalance from lopsided
    directory trees."""
    label_of = label_generator or (lambda p: p.parent.name)

    def filt(paths: List[Path]) -> List[Path]:
        by_label: dict = {}
        for p in paths:
            by_label.setdefault(label_of(p), []).append(p)
        rng = random.Random(seed)
        out: List[Path] = []
        for label in sorted(by_label):
            group = by_label[label]
            if len(group) > max_per_class:
                group = rng.sample(group, max_per_class)
            out.extend(group)
        return out

    return filt


def load_numeric_csv(path, delimiter: str = ",", skip_lines: int = 0) -> "np.ndarray":
    """Bulk-load an all-numeric CSV as a float32 matrix.

    The DataVec-role native fast path: parses in C++
    (native/dl4jtpu_io.cpp, multithreaded) when the library is built,
    otherwise numpy.  Use this instead of iterating CSVRecordReader when
    the file is purely numeric and large.
    """
    import numpy as np

    from deeplearning4j_tpu.runtime import native

    if native.available():
        try:
            return native.csv_read_f32(str(path), delimiter, skip_lines)
        except (IOError, RuntimeError):
            pass
    return np.loadtxt(path, delimiter=delimiter, skiprows=skip_lines,
                      dtype=np.float32, ndmin=2)


class JDBCRecordReader(RecordReader):
    """SQL-backed records — the `org.datavec.jdbc.records.reader.impl.
    JDBCRecordReader` role.  Python's DB-API replaces JDBC: pass any
    DB-API connection (sqlite3 ships in the stdlib) or a sqlite path, plus
    the query.  Each row becomes one record; parameters are bound
    server-side (no string splicing).

        rr = JDBCRecordReader("data.db", "SELECT f1, f2, label FROM train")
    """

    def __init__(self, conn_or_path, query: str, parameters: tuple = ()):
        if isinstance(conn_or_path, (str, os.PathLike)):
            import sqlite3

            # check_same_thread=False: AsyncDataSetIterator consumes readers
            # from a producer thread; access is serialized per pass anyway
            self._conn = sqlite3.connect(
                str(conn_or_path), check_same_thread=False
            )
            self._owns = True
        else:
            self._conn = conn_or_path
            self._owns = False
        self.query = query
        self.parameters = tuple(parameters)

    def __iter__(self):
        cur = self._conn.cursor()
        try:
            cur.execute(self.query, self.parameters)
            for row in cur:
                yield list(row)
        finally:
            # a partially-consumed generator may be finalized AFTER the
            # connection was closed (GeneratorExit at GC time); closing a
            # cursor on a closed connection raises in sqlite3
            try:
                cur.close()
            except Exception:
                pass

    def column_names(self) -> list[str]:
        if getattr(self, "_columns", None) is None:
            cur = self._conn.cursor()
            try:
                # LIMIT 0 wrapper: cursor.description is populated without
                # the server executing the full (possibly expensive) query.
                # Subquery alias is mandatory on PostgreSQL.
                try:
                    cur.execute(
                        f"SELECT * FROM ({self.query}) AS _cols LIMIT 0",
                        self.parameters,
                    )
                except Exception:
                    # a failed statement can abort an open transaction
                    # (PostgreSQL): roll back before the plain fallback
                    try:
                        self._conn.rollback()
                    except Exception:
                        pass
                    cur.close()
                    cur = self._conn.cursor()
                    cur.execute(self.query, self.parameters)
                self._columns = [d[0] for d in cur.description]
            finally:
                cur.close()
        return self._columns

    def close(self) -> None:
        if self._owns:
            self._conn.close()


class CSVSequenceRecordReader(RecordReader):
    """Per-file sequences — the `CSVSequenceRecordReader` role: each CSV
    file under `directory` (sorted by name) is ONE sequence; every line is
    a timestep record.  Iterating yields sequences (list of records);
    `sequence_lengths()` exposes the ragged lengths for masking.
    """

    def __init__(self, directory: str | os.PathLike, skip_lines: int = 0,
                 delimiter: str = ",", glob: str = "*.csv"):
        self.directory = Path(directory)
        self.skip_lines = skip_lines
        self.delimiter = delimiter
        self.glob = glob
        self._paths = sorted(self.directory.glob(glob))
        if not self._paths:
            raise FileNotFoundError(
                f"no files matching {glob!r} under {self.directory}"
            )
        self._lengths: list[int] | None = None

    def __iter__(self):
        lengths = []
        for p in self._paths:
            reader = CSVRecordReader(p, skip_lines=self.skip_lines,
                                     delimiter=self.delimiter)
            seq = list(reader)
            lengths.append(len(seq))
            yield seq
        self._lengths = lengths

    def num_sequences(self) -> int:
        return len(self._paths)

    def sequence_lengths(self) -> list[int]:
        """Ragged per-sequence lengths (cached — computing them must not
        cost a second full parse of every file).  Counts exactly what
        iteration yields: blank rows are skipped, skip_lines only eats
        real leading rows."""
        if self._lengths is None:
            lengths = []
            for p in self._paths:
                with open(p, newline="") as f:
                    n = sum(
                        1
                        for i, row in enumerate(csv.reader(f, delimiter=self.delimiter))
                        if i >= self.skip_lines and row
                    )
                lengths.append(n)
            self._lengths = lengths
        return self._lengths
