"""Shared training-step machinery for SequentialModel and GraphModel."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.layers import AUX_LOSS_KEY
from deeplearning4j_tpu.nn.losses import FUSED_ACTIVATION_LOSSES, Loss

CANONICAL_ACTIVATION = {
    Loss.MCXENT: Activation.SOFTMAX,
    Loss.NEGATIVELOGLIKELIHOOD: Activation.SOFTMAX,
    Loss.SPARSE_MCXENT: Activation.SOFTMAX,
    Loss.XENT: Activation.SIGMOID,
}


def resolve_output_spec(layer) -> tuple[Loss, Activation, bool]:
    """(loss, output_activation, fused) for an Output/Loss layer.

    fused=True: the training loss runs on logits via the numerically-stable
    fused softmax/sigmoid path, because the declared activation IS the
    loss's canonical one.  fused=False: the activation is applied before
    the loss so training optimizes exactly the function output() serves.
    """
    loss = layer.loss
    canonical = CANONICAL_ACTIVATION.get(loss, Activation.IDENTITY)
    act = layer.activation if layer.activation is not None else canonical
    fused = loss in FUSED_ACTIVATION_LOSSES and act == canonical
    return loss, act, fused


def mask_frozen_tx(tx, frozen_names: set[str]):
    """Route frozen layers around the ENTIRE optimizer transform — a frozen
    layer must not even be touched by decoupled weight decay."""
    if not frozen_names:
        return tx

    def trainable_mask(params):
        return {
            name: jax.tree.map(lambda _: name not in frozen_names, sub)
            for name, sub in params.items()
        }

    def frozen_mask(params):
        return {
            name: jax.tree.map(lambda _: name in frozen_names, sub)
            for name, sub in params.items()
        }

    return optax.chain(
        optax.masked(tx, trainable_mask),
        optax.masked(optax.set_to_zero(), frozen_mask),
    )


def pop_aux_losses(new_state: dict):
    """Split layer-emitted auxiliary losses (MoE load balancing etc.) out of
    the state tree: returns (aux_total, cleaned_state).  Aux entries are
    training-step byproducts, not persistent state — they must feed the
    objective, never the carried net_state."""
    total = jnp.zeros((), jnp.float32)
    cleaned = {}
    for lname, ls in new_state.items():
        if AUX_LOSS_KEY in ls:
            total = total + ls[AUX_LOSS_KEY]
            ls = {k: v for k, v in ls.items() if k != AUX_LOSS_KEY}
        if ls:
            cleaned[lname] = ls
    return total, cleaned


def regularization_loss(params, named_layers) -> jax.Array:
    """Sum of per-layer l1*|W| + 0.5*l2*W^2 penalties over REGULARIZED params.

    named_layers: iterable of (name, LayerConfig).
    """
    reg = jnp.zeros((), jnp.float32)
    for name, layer in named_layers:
        lp = params.get(name)
        if not lp:
            continue
        for l1, l2, w in layer.regularization_terms(lp):
            w = w.astype(jnp.float32)
            if l1:
                reg = reg + l1 * jnp.sum(jnp.abs(w))
            if l2:
                reg = reg + 0.5 * l2 * jnp.sum(w * w)
    return reg
