from deeplearning4j_tpu.models.model import Model
from deeplearning4j_tpu.models.sequential import SequentialModel
from deeplearning4j_tpu.models.computation_graph import GraphModel

__all__ = ["Model", "SequentialModel", "GraphModel"]
