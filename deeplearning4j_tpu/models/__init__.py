from deeplearning4j_tpu.models.model import Model
from deeplearning4j_tpu.models.sequential import SequentialModel

__all__ = ["Model", "SequentialModel"]
