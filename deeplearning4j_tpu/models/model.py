"""Base Model API — the `org.deeplearning4j.nn.api.Model` role.

Common surface shared by SequentialModel (MultiLayerNetwork role) and
GraphModel (ComputationGraph role): init, fit, output, score, params
accounting, listener dispatch, save/load.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.utils.pytree import param_count, tree_flatten_with_paths


class Model:
    def __init__(self):
        self.params: Any = None        # pytree {layer_name: {param_name: array}}
        self.net_state: Any = None     # pytree of non-trainable state (BN stats...)
        self.opt_state: Any = None     # optax state (updaterState.bin role)
        self.iteration: int = 0
        self.epoch: int = 0
        self.listeners: list[TrainingListener] = []
        self.last_batch_size: int = 0
        self._last_score = None

    # -- listeners ---------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener: TrainingListener) -> None:
        self.listeners.append(listener)

    def _dispatch_iteration(self, score) -> None:
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch, score)

    def _finish_grouped_steps(self, losses, k: int) -> None:
        """Bookkeeping after a program that ran k optimizer steps (TBPTT
        windows or steps_per_execution groups): score/iteration update,
        and — only when listeners exist — ONE D2H transfer of all k losses
        followed by per-step dispatch with host scalars."""
        self._last_score = losses   # (k,) device array; score_value reads [-1]
        self.iteration += k
        if self.listeners:
            host_losses = np.asarray(losses)
            self.iteration -= k
            done = 0
            try:
                for w in range(k):
                    self._last_score = host_losses[w]
                    self.iteration += 1
                    done += 1
                    self._dispatch_iteration(host_losses[w])
            finally:
                # a throwing listener must not leave the counter rewound —
                # all k steps DID run on device
                self.iteration += k - done

    # -- params ------------------------------------------------------------
    def num_params(self) -> int:
        if self.params is None:
            raise RuntimeError("model not initialized; call init()")
        return param_count(self.params)

    def param_table(self) -> dict[str, np.ndarray]:
        """Flattened name->array view (the reference's paramTable())."""
        return {k: np.asarray(v) for k, v in tree_flatten_with_paths(self.params)}

    @property
    def score_value(self) -> float:
        """Last training loss (reference `Model.score()`); device-syncs.
        A non-scalar score (the TBPTT step returns all window losses as one
        array to avoid a device round-trip per window) reads as its final
        entry."""
        if self._last_score is None:
            return float("nan")
        s = np.asarray(self._last_score)
        return float(s.ravel()[-1]) if s.ndim else float(s)

    # -- persistence (implemented in train.checkpoint) ---------------------
    def save(self, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        ModelSerializer.write_model(self, path, save_updater)
