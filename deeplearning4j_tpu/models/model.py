"""Base Model API — the `org.deeplearning4j.nn.api.Model` role.

Common surface shared by SequentialModel (MultiLayerNetwork role) and
GraphModel (ComputationGraph role): init, fit, output, score, params
accounting, listener dispatch, save/load.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.utils.pytree import param_count, tree_flatten_with_paths


class Model:
    def __init__(self):
        self.params: Any = None        # pytree {layer_name: {param_name: array}}
        self.net_state: Any = None     # pytree of non-trainable state (BN stats...)
        self.opt_state: Any = None     # optax state (updaterState.bin role)
        self.iteration: int = 0
        self.epoch: int = 0
        self.listeners: list[TrainingListener] = []
        self.last_batch_size: int = 0
        self._last_score = None
        # ETL accounting: seconds fit() sat blocked on the input iterator
        # (decode/tokenize/disk — anything the device waited for)
        self.etl_wait_s: float = 0.0        # cumulative across fits
        self.last_etl_wait_s: float = 0.0   # wait before the latest batch
        from deeplearning4j_tpu.runtime import compile_stats as _cs

        self._compile_snap = _cs.snapshot()   # baseline at model creation

    # -- input-pipeline accounting ----------------------------------------
    def _timed_batches(self, iterator):
        """Iterate `iterator`, charging time blocked on next() to
        etl_wait_s.  Every fit loop pulls batches through this, so the
        iterator-starvation tax (JPEG decode, tokenization, disk) is a
        first-class metric next to samples/sec instead of silently
        deflating it.  Near-zero when AsyncDataSetIterator's producer
        keeps ahead of the device.  Each wait also lands on the
        telemetry spine: the `dl4jtpu_etl_wait_seconds_total` counter
        and, when tracing is on, an `etl_wait` span opening the step's
        host timeline."""
        from deeplearning4j_tpu.observe.metrics import registry
        from deeplearning4j_tpu.observe.trace import tracer
        from deeplearning4j_tpu.runtime import faults

        reg = registry()
        wait_total = reg.counter("dl4jtpu_etl_wait_seconds_total")
        batches_total = reg.counter("dl4jtpu_etl_batches_total")
        rec = tracer()
        it = iter(iterator)
        while True:
            t0 = time.perf_counter()
            try:
                # fault site: every batch pull in every fit loop (armed
                # plans provoke the flaky-input-pipeline failure mode;
                # disarmed this is one attribute check)
                faults.maybe_fail("data.next_batch")
                batch = next(it)
            except StopIteration:
                return
            wait = time.perf_counter() - t0
            self.last_etl_wait_s = wait
            self.etl_wait_s += wait
            wait_total.inc(wait)
            batches_total.inc()
            rec.add_complete("etl_wait", t0, wait, cat="step_phase")
            yield batch

    def _observe_step(self, n_steps: int = 1):
        """StepScope for the next dispatched step program: observes the
        step-latency histogram always, and the per-phase host spans
        (host_stage/dispatch/device_sync/listeners) when the global
        tracer is enabled.  Every fit path wraps its program dispatch
        in one of these."""
        from deeplearning4j_tpu.observe.trace import step_scope

        return step_scope(self, n_steps)

    def compile_stats(self) -> dict:
        """Compile-tax counters since this model was constructed, plus
        `step_programs` — the number of DISTINCT XLA programs compiled
        for this model's cached step functions (one per (step kind,
        shape signature); the recompile counter the bucketing tests
        assert on)."""
        from deeplearning4j_tpu.runtime import compile_stats as _cs

        d = (_cs.snapshot() - self._compile_snap).as_dict()
        d["step_programs"] = sum(
            fn._cache_size()
            for fn in getattr(self, "_step_fns", {}).values()
            if hasattr(fn, "_cache_size")
        )
        return d

    # -- listeners ---------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener: TrainingListener) -> None:
        self.listeners.append(listener)

    def _dispatch_iteration(self, score) -> None:
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch, score)

    def _finish_grouped_steps(self, losses, k: int) -> None:
        """Bookkeeping after a program that ran k optimizer steps (TBPTT
        windows or steps_per_execution groups): score/iteration update,
        and — only when listeners exist — ONE D2H transfer of all k losses
        followed by per-step dispatch with host scalars."""
        from deeplearning4j_tpu.observe.trace import tracer

        rec = tracer()
        self._last_score = losses   # (k,) device array; score_value reads [-1]
        self.iteration += k
        if self.listeners:
            # no device_sync span here: every grouped caller already
            # emitted one around obs.sync, and a second ~0us span would
            # double-count the phase in the timeline
            host_losses = np.asarray(losses)
            self.iteration -= k
            done = 0
            try:
                with rec.span("listeners", cat="step_phase"):
                    for w in range(k):
                        self._last_score = host_losses[w]
                        self.iteration += 1
                        done += 1
                        self._dispatch_iteration(host_losses[w])
            finally:
                # a throwing listener must not leave the counter rewound —
                # all k steps DID run on device
                self.iteration += k - done

    # -- params ------------------------------------------------------------
    def num_params(self) -> int:
        if self.params is None:
            raise RuntimeError("model not initialized; call init()")
        return param_count(self.params)

    def param_table(self) -> dict[str, np.ndarray]:
        """Flattened name->array view (the reference's paramTable())."""
        return {k: np.asarray(v) for k, v in tree_flatten_with_paths(self.params)}

    @property
    def score_value(self) -> float:
        """Last training loss (reference `Model.score()`); device-syncs.
        A non-scalar score (the TBPTT step returns all window losses as one
        array to avoid a device round-trip per window) reads as its final
        entry."""
        if self._last_score is None:
            return float("nan")
        s = np.asarray(self._last_score)
        return float(s.ravel()[-1]) if s.ndim else float(s)

    # -- persistence (implemented in train.checkpoint) ---------------------
    def save(self, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        ModelSerializer.write_model(self, path, save_updater)
