"""Base Model API — the `org.deeplearning4j.nn.api.Model` role.

Common surface shared by SequentialModel (MultiLayerNetwork role) and
GraphModel (ComputationGraph role): init, fit, output, score, params
accounting, listener dispatch, save/load.
"""

from __future__ import annotations

import logging
import time
from typing import Any

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.utils.pytree import param_count, tree_flatten_with_paths

log = logging.getLogger("deeplearning4j_tpu")


class _LazyScores:
    """The k device losses of one grouped program, materialized host-side
    AT MOST ONCE — on the first listener that actually reads a score
    (one batched transfer) instead of unconditionally at program exit.
    A fit whose listeners never read scores (checkpointing, ETA logging)
    never blocks on the device at all."""

    __slots__ = ("_device", "_host")

    def __init__(self, device_losses):
        self._device = device_losses
        self._host = None

    def fetch(self) -> np.ndarray:
        if self._host is None:
            self._host = np.asarray(self._device)
            self._device = None           # drop the device handle
        return self._host

    def __getitem__(self, i: int) -> "_LazyScore":
        return _LazyScore(self, i)


class _LazyScore:
    """One step's score from a _LazyScores group: quacks like the host
    float listeners always received — conversion, formatting,
    comparison and arithmetic all work — but defers the D2H sync until
    the first such numeric read actually happens."""

    __slots__ = ("_group", "_i")

    def __init__(self, group: _LazyScores, i: int):
        self._group = group
        self._i = i

    def __float__(self) -> float:
        return float(self._group.fetch()[self._i])

    def __array__(self, dtype=None, copy=None):
        a = np.asarray(self._group.fetch()[self._i])
        return a.astype(dtype) if dtype is not None else a

    def __format__(self, spec: str) -> str:
        return format(float(self), spec)

    def __repr__(self) -> str:
        return repr(float(self))

    def __bool__(self) -> bool:
        return bool(float(self))

    def __int__(self) -> int:
        return int(float(self))

    # duck-typed listeners compare and accumulate scores (`score <
    # best`, `total += score`); each delegates to the batched fetch
    def __lt__(self, other):
        return float(self) < other

    def __le__(self, other):
        return float(self) <= other

    def __gt__(self, other):
        return float(self) > other

    def __ge__(self, other):
        return float(self) >= other

    def __eq__(self, other):
        return float(self) == other

    def __ne__(self, other):
        return float(self) != other

    def __hash__(self):
        return hash(float(self))

    def __add__(self, other):
        return float(self) + other

    __radd__ = __add__

    def __sub__(self, other):
        return float(self) - other

    def __rsub__(self, other):
        return other - float(self)

    def __mul__(self, other):
        return float(self) * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        return float(self) / other

    def __rtruediv__(self, other):
        return other / float(self)

    def __neg__(self):
        return -float(self)

    def __abs__(self):
        return abs(float(self))


def _batch_nbytes(batch) -> int:
    """Total array bytes of a DataSet/MultiDataSet WITHOUT materializing
    anything: prefetch-staged batches hold device arrays, and an
    np.asarray here would be a D2H sync in the hot loop.  `nbytes` is a
    metadata read on both numpy and jax arrays."""
    from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet

    def nb(a):
        return int(getattr(a, "nbytes", 0) or 0) if a is not None else 0

    if isinstance(batch, DataSet):
        return (nb(batch.features) + nb(batch.labels)
                + nb(batch.features_mask) + nb(batch.labels_mask))
    if isinstance(batch, MultiDataSet):
        total = sum(nb(a) for a in batch.features)
        total += sum(nb(a) for a in batch.labels)
        for group in (batch.features_masks, batch.labels_masks):
            if group is not None:
                total += sum(nb(a) for a in group)
        return total
    return 0


def _poison_batch(batch):
    """The injected ``data.decode`` 'corrupt' action: a copy of the
    batch with every FLOAT feature/label array NaN-filled — same
    shapes/dtypes, the values a broken decoder would emit.  Masks are
    left alone (a corrupt record keeps its framing)."""
    from deeplearning4j_tpu.data.dataset import map_batch

    def bad(a):
        a = np.array(a, copy=True)
        if np.issubdtype(a.dtype, np.floating):
            a.fill(np.nan)
        return a

    return map_batch(batch, bad, masks=False)


class Model:
    def __init__(self):
        self.params: Any = None        # pytree {layer_name: {param_name: array}}
        self.net_state: Any = None     # pytree of non-trainable state (BN stats...)
        self.opt_state: Any = None     # optax state (updaterState.bin role)
        self.iteration: int = 0
        self.epoch: int = 0
        self.listeners: list[TrainingListener] = []
        self.last_batch_size: int = 0
        self._last_score = None
        # ETL accounting: seconds fit() sat blocked on the input iterator
        # (decode/tokenize/disk — anything the device waited for)
        self.etl_wait_s: float = 0.0        # cumulative across fits
        self.last_etl_wait_s: float = 0.0   # wait before the latest batch
        # Pipelining accounting: producer-thread staging seconds hidden
        # behind device compute (PrefetchIterator), accumulated between
        # step scopes and stamped onto the train_step span
        self.last_overlap_s: float = 0.0
        self._overlap_accum: float = 0.0
        # one-time per fit: donated trees must not be aliased by listeners
        self._donation_checked: bool = True
        # self-healing hooks: a StepWatchdog armed by the step scopes
        # (created at fit entry when flags.watchdog_enabled), and the
        # RecoveryPolicy the fit chokepoints route through when attached
        self._watchdog = None
        self._recovery = None
        # device-compiled data pipeline: the lowered DeviceDecode the
        # fused fit chokepoints compose in front of the step program
        # (set for the duration of a fit over an advertising iterator)
        self._device_decode = None
        # ZeRO-1 sharded weight update: the Zero1Placement installed by
        # distribute(zero=1) (parallel/zero.py); None = the replicated
        # update epilogue.  _placements remembers every tree's leaf
        # shardings so recovery can re-place restored checkpoints.
        self._zero_placement = None
        self._placements = None
        # device-resident step counters of the grouped/TBPTT programs
        # (recovery resets them after a rollback rewinds `iteration`)
        self._multi_iter_dev = None
        self._tbptt_iter_dev = None
        # performance attribution: the cost-registry record of the last
        # program this model dispatched (set by the registration wrapper
        # during the call; StepScope.sync() snapshots it)
        self._cost_program = None
        from deeplearning4j_tpu.runtime import compile_stats as _cs

        self._compile_snap = _cs.snapshot()   # baseline at model creation

    # -- input-pipeline accounting ----------------------------------------
    def _timed_batches(self, iterator):
        """Iterate `iterator`, charging time blocked on next() to
        etl_wait_s.  Every fit loop pulls batches through this, so the
        iterator-starvation tax (JPEG decode, tokenization, disk) is a
        first-class metric next to samples/sec instead of silently
        deflating it.  Near-zero when AsyncDataSetIterator's producer
        keeps ahead of the device.  Each wait also lands on the
        telemetry spine: the `dl4jtpu_etl_wait_seconds_total` counter
        and, when tracing is on, an `etl_wait` span opening the step's
        host timeline."""
        from deeplearning4j_tpu.observe.metrics import registry
        from deeplearning4j_tpu.observe.trace import tracer
        from deeplearning4j_tpu.runtime import faults

        reg = registry()
        wait_total = reg.counter("dl4jtpu_etl_wait_seconds_total")
        batches_total = reg.counter("dl4jtpu_etl_batches_total")
        overlap_total = reg.counter(
            "dl4jtpu_prefetch_overlap_seconds_total"
        )
        h2d_total = reg.counter("dl4jtpu_h2d_bytes_total")
        rec = tracer()
        it = iter(iterator)
        absorbed_pull_failure = False
        no_batch = object()
        while True:
            t0 = time.perf_counter()
            batch = no_batch
            try:
                # fault site: every batch pull in every fit loop (armed
                # plans provoke the flaky-input-pipeline failure mode;
                # disarmed this is one attribute check)
                faults.maybe_fail("data.next_batch")
                batch = next(it)
                # fault site: the per-batch decode boundary, AFTER the
                # pull — 'corrupt' poisons the batch (a decoder emitting
                # garbage), 'raise' is a per-record decode failure.
                # Sited post-pull so a raise never tears the iterator's
                # generator frame and the feed can continue.
                action = faults.maybe_fail("data.decode")
                if action == "corrupt":
                    batch = _poison_batch(batch)
                absorbed_pull_failure = False
            except StopIteration:
                if absorbed_pull_failure:
                    # a generator-backed iterator cannot resume after
                    # raising — the quarantined pull may have ended the
                    # feed early, and a silently short epoch must not
                    # read as a clean one
                    log.warning(
                        "feed ended immediately after a quarantined pull "
                        "failure; generator-backed iterators cannot "
                        "resume, so any remaining batches this epoch "
                        "were skipped"
                    )
                return
            except Exception as exc:
                recov = self._recovery
                # the policy declines non-poison failures (host memory
                # pressure, programming errors — recovery.NON_POISON_ERRORS)
                # and they re-raise below; a failure AT the decode
                # boundary leaves the pulled batch in hand — forward it
                # so the quarantine record carries replayable bytes,
                # not just metadata
                if (recov is not None
                        and recov.quarantine_pull_failure(
                            self, exc,
                            batch=None if batch is no_batch else batch,
                        )):
                    absorbed_pull_failure = True
                    continue      # absorbed (bounded by the quarantine cap)
                raise
            wait = time.perf_counter() - t0
            batches_total.inc()
            source = getattr(batch, "_etl_source", None)
            if source is not None:
                # cache replay: the pull cost is mmap/page-cache time,
                # not input-pipeline starvation — attribute it to its
                # own labeled series instead of inflating ETL wait
                self.last_etl_wait_s = 0.0
                wait_total.inc(wait, source=source)
                rec.add_complete("etl_wait", t0, wait, cat="step_phase",
                                 source=source)
            else:
                self.last_etl_wait_s = wait
                self.etl_wait_s += wait
                wait_total.inc(wait)
                rec.add_complete("etl_wait", t0, wait, cat="step_phase")
            nbytes = _batch_nbytes(batch)
            if nbytes:
                # what this batch costs to cross host->HBM: raw uint8
                # bytes on the fused-decode feed, host-transformed
                # floats otherwise — the attributable H2D delta of
                # moving the decode onto the device
                h2d_total.inc(
                    nbytes,
                    feed="raw" if getattr(
                        batch, "_raw_for_device_decode", False
                    ) else "decoded",
                )
            stage_s = getattr(batch, "_prefetch_stage_s", None)
            if stage_s is not None:
                # producer work not re-paid as consumer wait = the
                # seconds the prefetch pipeline hid behind compute
                overlap = max(0.0, stage_s - wait)
                self.last_overlap_s = overlap
                self._overlap_accum += overlap
                if overlap > 0:
                    overlap_total.inc(overlap)
            else:
                self.last_overlap_s = 0.0
            yield batch

    def _fit_one(self, batch) -> None:
        """The single-batch chokepoint every per-batch fit loop routes
        through: plain fit_batch normally; the attached RecoveryPolicy's
        envelope (skip-window, input scan, OOM microbatch split,
        divergence rollback) when one is installed.  The planned
        StepProgram executor inherits recovery by keeping this the one
        entry point."""
        recov = self._recovery
        if recov is None:
            self.fit_batch(batch)
        else:
            recov.run_step(self, batch)

    def _fit_group(self, batches, runner) -> None:
        """The grouped-program chokepoint (steps_per_execution /
        grouped-TBPTT): `runner(batches)` dispatches the k-step program;
        the RecoveryPolicy wraps it when attached."""
        recov = self._recovery
        if recov is None:
            runner(batches)
        else:
            recov.run_group(self, batches, runner)

    def _ensure_watchdog(self):
        """Create this model's StepWatchdog at fit entry (lazily, once)
        when flags enable it; the step scopes arm it around every
        dispatched program.  One shared monitor thread serves every
        watchdog in the process."""
        if self._watchdog is None:
            from deeplearning4j_tpu.runtime.flags import environment

            env = environment()
            if env.watchdog_enabled:
                from deeplearning4j_tpu.runtime.watchdog import StepWatchdog

                self._watchdog = StepWatchdog(
                    floor_s=env.watchdog_floor_s, k=env.watchdog_k,
                    name=type(self).__name__,
                )
        return self._watchdog

    def _observe_step(self, n_steps: int = 1):
        """StepScope for the next dispatched step program: observes the
        step-latency histogram always, and the per-phase host spans
        (host_stage/dispatch/device_sync/listeners) when the global
        tracer is enabled.  Every fit path wraps its program dispatch
        in one of these."""
        from deeplearning4j_tpu.observe.trace import step_scope

        return step_scope(self, n_steps)

    def _device_decode_feed(self, iterator, unsupported_reason=None):
        """The device-compiled data pipeline's fit-entry decision: when
        `iterator` advertises a device-lowerable transform chain
        (datavec/device.py) and flags.device_decode is on, switch the
        feed to tagged raw batches and return the lowered DeviceDecode
        the fused fit chokepoints compose in front of the step program.

        Returns ``(feed, decode|None)``.  Every fallback — flag off is
        silent; a non-lowerable chain or an unsupported fit variant
        logs its reason and counts on
        ``dl4jtpu_device_decode_fallbacks_total`` — keeps the original
        iterator, whose own ``__iter__`` applies the chain on the host
        (same numerics, no fusion)."""
        from deeplearning4j_tpu.datavec import device as dv
        from deeplearning4j_tpu.runtime.flags import environment

        if not environment().device_decode:
            return iterator, None
        chain = dv.chain_of(iterator)
        if chain is None:
            return iterator, None
        reason = unsupported_reason
        decode = None
        if reason is None:
            decode, reason = dv.try_lower(chain)
        if decode is None:
            from deeplearning4j_tpu.observe.metrics import registry

            log.info(
                "device decode fallback (transforms stay on the host): %s",
                reason,
            )
            registry().counter(
                "dl4jtpu_device_decode_fallbacks_total"
            ).inc(reason=reason)
            return iterator, None
        return dv.raw_feed(iterator, decode), decode

    def _count_device_decode(self, decode, feats, labs, k: int = 1) -> None:
        """Per-dispatch accounting of the fused decode stage: batch
        count plus the calibrated per-signature device seconds (the
        fused program hides the stage, so attribution uses a standalone
        jitted decode timed once per input signature)."""
        from deeplearning4j_tpu.observe.metrics import registry

        reg = registry()
        reg.counter("dl4jtpu_device_decode_batches_total").inc(k)
        try:
            secs = decode.calibrated_seconds(feats, labs)
        except Exception as e:
            # calibration is attribution, never a failure: a signature
            # that refuses to time standalone still trains fused
            log.debug("device-decode calibration skipped: %s", e)
            return
        reg.counter("dl4jtpu_device_decode_seconds_total").inc(secs * k)

    def _prefetch_feed(self, iterator):
        """Wrap a fit iterator in the pipelining PrefetchIterator
        (flags.prefetch_depth deep; 0 disables).  The caller owns
        shutdown: close() the returned feed in a finally when it is not
        the original iterator.

        Multi-process/sharded models keep staging on the training
        thread (place_batch -> put_global forms global arrays and is
        not guaranteed re-entrant against a running step), so their
        wrap is pull-ahead only — ETL decode still overlaps compute,
        the device placement does not.

        Already-materialized in-memory feeds (ExistingDataSetIterator,
        NumpyDataSetIterator, plain lists — every `fit([batch, ...])`
        or `fit((x, y))` call) are exempt: they have no per-batch
        decode cost to hide, so the wrap would be pure thread-handoff
        tax on sub-millisecond steps.  Wrap explicitly in
        PrefetchIterator/AsyncDataSetIterator to overlap the H2D
        staging of a pre-decoded corpus."""
        from deeplearning4j_tpu.data.iterator import (
            AsyncDataSetIterator,
            ExistingDataSetIterator,
            NumpyDataSetIterator,
        )
        from deeplearning4j_tpu.data.prefetch import (
            PrefetchIterator, stage_to_device,
        )
        from deeplearning4j_tpu.runtime.flags import environment

        depth = environment().prefetch_depth
        if depth <= 0:
            return iterator
        if isinstance(iterator, (PrefetchIterator, AsyncDataSetIterator)):
            return iterator       # already pipelined; don't double-thread
        if isinstance(iterator, (ExistingDataSetIterator,
                                 NumpyDataSetIterator, list, tuple)):
            return iterator       # in-memory: nothing to hide
        stage = (
            None if getattr(self, "_batch_sharding", None) is not None
            else stage_to_device
        )
        return PrefetchIterator(iterator, depth=depth, stage=stage)

    def _check_donation_aliases(self) -> None:
        """One-time (per fit) guard for the jitted steps' donate_argnums:
        a listener that stashed a reference to model.params /
        opt_state / net_state during its first iteration_done would read
        donated (deleted) buffers after the NEXT step consumes them.
        Runs after the first listener dispatch — exactly when such a
        stash exists but before the second step invalidates it — and
        scans each listener's PUBLIC attributes for leaves aliasing the
        live trees.  Private (underscore) attributes are trusted to
        manage donation themselves (HealthListener keeps an old params
        DICT for identity comparison and jit-output COPIES for |Δw| —
        both safe by construction)."""
        import jax

        def buffer_keys(leaf):
            """Aliasing keys for one leaf: its Python identity plus —
            for jax Arrays — every addressable shard's device-buffer
            pointer.  A SHARDED tree (ZeRO-1 opt state) can be aliased
            through a different Python object (a shard view pulled off
            ``addressable_shards``, a re-wrapped jax.Array over the
            same buffers), which plain id() tracking would miss."""
            keys = [id(leaf)]
            shards = getattr(leaf, "addressable_shards", None)
            if shards is not None:
                for s in shards:
                    try:
                        keys.append(s.data.unsafe_buffer_pointer())
                    except Exception:
                        break     # backend without pointer introspection
            return keys

        live = {
            k
            for leaf in jax.tree.leaves(
                (self.params, self.opt_state, self.net_state)
            )
            for k in buffer_keys(leaf)
        }
        for lst in self.listeners:
            attrs = getattr(lst, "__dict__", None)
            if not attrs:
                continue
            for attr, value in attrs.items():
                if attr.startswith("_"):
                    continue
                try:
                    leaves = jax.tree.leaves(value)
                except Exception:
                    continue      # exotic containers: not our trees
                for leaf in leaves:
                    if any(k in live for k in buffer_keys(leaf)):
                        raise RuntimeError(
                            f"listener {type(lst).__name__}.{attr} "
                            "aliases the model's live param/opt-state "
                            "buffers; the next training step DONATES "
                            "those buffers to XLA and the reference "
                            "would read freed memory.  Copy instead "
                            "(np.asarray / jax.tree.map(jnp.copy, ...)) "
                            "or snapshot via train.listeners."
                            "_host_snapshot."
                        )

    def _apply_grads(self, params, opt_state, grads):
        """The SHARED update epilogue every step program traces (single,
        grouped scan, TBPTT window, fused decode — Sequential and
        Graph): optax update + param apply.  With the Zero1Placement
        distribute(zero=1) installs, the same call becomes the sharded
        epilogue — reduce-scatter grads, per-shard update against the
        sharded opt state, all-gather params — so every step variant
        differs from its replicated twin ONLY in update layout."""
        import jax

        zero = self._zero_placement
        if zero is not None:
            return zero.apply(self._tx, params, opt_state, grads)
        updates, opt_state = self._tx.update(grads, opt_state, params)
        params = jax.tree.map(
            lambda p, u: (p + u.astype(p.dtype)), params, updates
        )
        return params, opt_state

    def _step_key_suffix(self) -> tuple:
        """Step-fn cache/program-registry key markers for program
        variants that trace to DIFFERENT XLA programs over the same
        model: the ZeRO-1 sharded update epilogue, and int8-quantized
        params (quant/ptq.py) whose dequant-matmul forwards read 1/4
        the weight bytes — the cost registry must not attribute one
        variant's flops/bytes/roofline analysis to the other."""
        suffix = ()
        zero = self._zero_placement
        if zero is not None:
            from deeplearning4j_tpu.parallel.zero import Zero2Placement

            if isinstance(zero, Zero2Placement):
                # the accumulation count changes the traced program
                # (scan length), not just the sharding annotations
                suffix += (f"zero2x{zero.accum}",)
            else:
                suffix += ("zero1",)
        if getattr(self, "_quantized", None) is not None:
            suffix += ("int8",)
        return suffix

    def _register_program(self, key, fn):
        """Register a freshly built step program with the cost registry
        (observe/cost.py) and return the instrumented wrapper the
        builder caches in ``_step_fns``.  The registry entry lives
        exactly as long as the cache entry — ``_step_fns.clear()``
        (recovery's LR retrace, re-distribute) evicts it."""
        from deeplearning4j_tpu.observe import cost

        return cost.register_step_program(self, key, fn)

    def compile_stats(self) -> dict:
        """Compile-tax counters since this model was constructed, plus
        `step_programs` — the number of DISTINCT XLA programs compiled
        for this model's cached step functions (one per (step kind,
        shape signature); the recompile counter the bucketing tests
        assert on)."""
        from deeplearning4j_tpu.runtime import compile_stats as _cs

        d = (_cs.snapshot() - self._compile_snap).as_dict()
        d["step_programs"] = sum(
            fn._cache_size()
            for fn in getattr(self, "_step_fns", {}).values()
            if hasattr(fn, "_cache_size")
        )
        return d

    # -- listeners ---------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener: TrainingListener) -> None:
        self.listeners.append(listener)

    def _dispatch_iteration(self, score) -> None:
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, self.epoch, score)
        if not self._donation_checked:
            # after the FIRST dispatch of a fit: any stash a listener
            # just took still aliases the live trees, and the second
            # step has not yet donated them — the one moment the
            # use-after-donate hazard is both present and harmless
            self._donation_checked = True
            if self.listeners:
                self._check_donation_aliases()

    def _finish_grouped_steps(self, losses, k: int) -> None:
        """Bookkeeping after a program that ran k optimizer steps (TBPTT
        windows or steps_per_execution groups): score/iteration update,
        and per-step listener dispatch with LAZY scores — the k device
        losses are fetched host-side at most once (one batched D2H
        transfer), and only when a listener actually reads a score.
        Log-every-K listeners therefore sync at THEIR cadence instead of
        every group."""
        from deeplearning4j_tpu.observe.trace import tracer

        rec = tracer()
        self._last_score = losses   # (k,) device array; score_value reads [-1]
        self.iteration += k
        if self.listeners:
            # no device_sync span here: every grouped caller already
            # emitted one around obs.sync, and a second ~0us span would
            # double-count the phase in the timeline
            lazy = _LazyScores(losses)
            self.iteration -= k
            done = 0
            try:
                with rec.span("listeners", cat="step_phase"):
                    for w in range(k):
                        self._last_score = lazy[w]
                        self.iteration += 1
                        done += 1
                        self._dispatch_iteration(lazy[w])
            finally:
                # a throwing listener must not leave the counter rewound —
                # all k steps DID run on device
                self.iteration += k - done

    # -- params ------------------------------------------------------------
    def num_params(self) -> int:
        if self.params is None:
            raise RuntimeError("model not initialized; call init()")
        return param_count(self.params)

    def param_table(self) -> dict[str, np.ndarray]:
        """Flattened name->array view (the reference's paramTable())."""
        return {k: np.asarray(v) for k, v in tree_flatten_with_paths(self.params)}

    @property
    def score_value(self) -> float:
        """Last training loss (reference `Model.score()`); device-syncs.
        A non-scalar score (the TBPTT step returns all window losses as one
        array to avoid a device round-trip per window) reads as its final
        entry."""
        if self._last_score is None:
            return float("nan")
        s = np.asarray(self._last_score)
        return float(s.ravel()[-1]) if s.ndim else float(s)

    # -- persistence (implemented in train.checkpoint) ---------------------
    def save(self, path: str, save_updater: bool = True) -> None:
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        ModelSerializer.write_model(self, path, save_updater)
