"""SequentialModel — the MultiLayerNetwork role, compiled whole-step.

The reference's MultiLayerNetwork.fit() interprets the layer stack op-by-op
across JNI per minibatch (SURVEY.md §3.1: feedForwardToLayer →
calcBackpropGradients → updater, one native call per op).  Here the ENTIRE
training iteration — forward, loss (+regularization), backward, gradient
clipping, updater, BN-stat update — is ONE jit-compiled XLA computation
with donated param/opt-state buffers: zero host round-trips inside a step,
everything resident in HBM, elementwise work fused into the matmuls.

This is the north-star differentiator named in BASELINE.json.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator, NumpyDataSetIterator
from deeplearning4j_tpu.models._cast import entry_cast
from deeplearning4j_tpu.models.model import Model
from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.neural_net_configuration import SequentialConfiguration
from deeplearning4j_tpu.nn.losses import Loss, compute as compute_loss
from deeplearning4j_tpu.nn.updaters import with_gradient_clipping
from deeplearning4j_tpu.models._common import (
    mask_frozen_tx,
    pop_aux_losses,
    regularization_loss,
    resolve_output_spec,
)
from deeplearning4j_tpu.runtime.backend import backend
from deeplearning4j_tpu.runtime.rng import SeedStream


def _as_iterator(data, batch_size: int | None) -> DataSetIterator:
    if isinstance(data, DataSetIterator):
        return data
    if isinstance(data, DataSet):
        from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator

        if batch_size:
            return ExistingDataSetIterator(data.split_batches(batch_size))
        return ExistingDataSetIterator([data])
    if isinstance(data, tuple) and len(data) == 2:
        return NumpyDataSetIterator(data[0], data[1], batch_size or 32)
    if isinstance(data, list) and data and all(
        isinstance(b, DataSet) for b in data
    ):
        # non-empty only: fit([]) must stay a loud error, not silent
        # zero-batch "training"
        from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator

        return ExistingDataSetIterator(data)
    raise TypeError(f"cannot interpret {type(data)} as training data")


class SequentialModel(Model):
    """Sequential layer stack with whole-step-compiled fit()."""

    def __init__(self, conf: SequentialConfiguration):
        super().__init__()
        self.conf = conf
        self._itypes = conf.layer_input_types()
        self._flatten_before = conf.flatten_flags()
        self._loss, self._out_activation, self._fused_loss = self._resolve_output()
        self._bf16 = (
            conf.bf16_compute if conf.bf16_compute is not None else backend().is_tpu
        )
        self._tx = with_gradient_clipping(
            conf.updater.to_optax(conf.steps_per_epoch),
            conf.gradient_clip_value,
            conf.gradient_clip_norm,
        )
        self._tx = self._mask_frozen(self._tx)
        self._stream = SeedStream(conf.seed)
        self._step_fns: dict[Any, Any] = {}
        self._rnn_runs = self._find_rnn_runs()

    def _find_rnn_runs(self) -> dict[int, int]:
        """Maximal runs (start index -> length) of >=2 consecutive
        recurrent layers that can execute as ONE fused time scan: no
        dropout on non-first members (fused stacks apply only the first
        layer's dropout) and no flatten boundary inside the run."""
        from deeplearning4j_tpu.nn.conf.recurrent import RecurrentLayerConfig

        runs: dict[int, int] = {}
        layers = self.conf.layers
        i = 0
        while i < len(layers):
            if not isinstance(layers[i], RecurrentLayerConfig):
                i += 1
                continue
            j = i + 1
            while (
                j < len(layers)
                and isinstance(layers[j], RecurrentLayerConfig)
                and not layers[j].dropout_rate
                and not self._flatten_before[j]
            ):
                j += 1
            if j - i >= 2:
                runs[i] = j - i
            i = j
        return runs

    # -- construction ------------------------------------------------------
    def _resolve_output(self) -> tuple[Loss, Activation, bool]:
        last = self.conf.layers[-1]
        # layers with their own loss function (e.g. Yolo2OutputLayer) bypass
        # the enum-based loss dispatch entirely; _with_params variants
        # (CenterLossOutputLayer) additionally see their own param dict
        self._custom_loss_layer = None
        if hasattr(last, "compute_loss_with_params"):
            self._custom_loss = last.compute_loss_with_params
            self._custom_loss_layer = last.name
            return Loss.MSE, Activation.IDENTITY, False
        if hasattr(last, "compute_loss"):
            self._custom_loss = last.compute_loss
            return Loss.MSE, Activation.IDENTITY, False
        self._custom_loss = None
        if not hasattr(last, "loss"):
            raise ValueError(
                "last layer must be an OutputLayer, RnnOutputLayer or "
                "LossLayer declaring the loss"
            )
        return resolve_output_spec(last)

    def _mask_frozen(self, tx):
        return mask_frozen_tx(tx, {l.name for l in self.conf.layers if l.frozen})

    def init(self) -> "SequentialModel":
        params, state = {}, {}
        for layer, itype in zip(self.conf.layers, self._itypes):
            p, s = layer.init(self._stream.key(f"init/{layer.name}"), itype)
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
        self.params = params
        self.net_state = state
        self.opt_state = self._tx.init(params)
        return self

    # -- pure forward (traced) --------------------------------------------
    def _forward(
        self, params, net_state, x, *, training: bool, rng, fmask=None, carries=None
    ):
        """carries: {rnn_layer_name: carry} initial RNN states (TBPTT /
        streaming inference); when given, the third return value holds the
        final carries.  fmask: (B, T) sequence mask threaded into
        mask-aware layers until the time axis collapses."""
        from deeplearning4j_tpu.nn.conf.recurrent import RecurrentLayerConfig

        x = entry_cast(x, self._bf16)
        new_state, new_carries = {}, {}
        mask = fmask
        plan = self._active_pipeline_plan()
        skip = set()
        if plan is not None:
            skip = set(range(plan.start, plan.end))
        fuse_until = -1
        for i, layer in enumerate(self.conf.layers):
            if i < fuse_until:
                continue
            if i in skip:
                if i == plan.start:
                    from deeplearning4j_tpu.parallel.pipeline import (
                        run_pipelined_segment,
                    )
                    from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS, active_mesh

                    if mask is not None:
                        raise ValueError(
                            "sequence masks are not supported through a "
                            "pipelined segment yet; drop the pipe axis or "
                            "the mask"
                        )
                    x = run_pipelined_segment(
                        plan, params, x, mesh=active_mesh(), axis=PIPE_AXIS,
                        training=training,
                    )
                continue
            if self._flatten_before[i]:
                x = x.reshape(x.shape[0], -1)
            run = self._rnn_runs.get(i, 0)
            if run >= 2 and not any((i + k) in skip for k in range(run)):
                from deeplearning4j_tpu.nn.conf.recurrent import fused_rnn_scan

                lys = self.conf.layers[i : i + run]
                cs = []
                for l in lys:
                    c = carries.get(l.name) if carries is not None else None
                    cs.append(c if c is not None else l.init_carry(x.shape[0], x.dtype))
                x, fins = fused_rnn_scan(
                    lys,
                    [params.get(l.name, {}) for l in lys],
                    x,
                    cs,
                    mask,
                    training=training,
                    rng=jax.random.fold_in(rng, i) if rng is not None else None,
                )
                if carries is not None:
                    for l, fc in zip(lys, fins):
                        new_carries[l.name] = fc
                fuse_until = i + run
                continue
            lp = params.get(layer.name, {})
            ls = net_state.get(layer.name, {})
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            if carries is not None and isinstance(layer, RecurrentLayerConfig):
                carry = carries.get(layer.name)
                if carry is None:
                    carry = layer.init_carry(x.shape[0], x.dtype)
                x, fin = layer.apply_with_carry(
                    lp, x, carry, mask=mask, training=training, rng=lrng
                )
                new_carries[layer.name] = fin
                ns = {}
            elif layer.ACCEPTS_MASK:
                x, ns = layer.apply(
                    lp, ls, x, training=training, rng=lrng, mask=mask
                )
            else:
                x, ns = layer.apply(lp, ls, x, training=training, rng=lrng)
            if ns:
                new_state[layer.name] = ns
            # once the time axis collapses (RNN -> FF), the mask is spent
            if self._itypes[i].kind == "rnn" and layer.output_type(self._itypes[i]).kind != "rnn":
                mask = None
        if carries is not None:
            return x, new_state, new_carries
        return x, new_state

    def _forward_range(self, params, net_state, x, lo: int, hi: int, *,
                       training: bool, rng):
        """Forward of layers [lo, hi) only — the pre/post-segment pieces of
        the 1F1B pipeline step (no masks/carries: the pipelined path
        rejects them before tracing).  bf16 cast applies at the network
        entry (lo == 0)."""
        if lo == 0:
            x = entry_cast(x, self._bf16)
        new_state = {}
        for i in range(lo, hi):
            layer = self.conf.layers[i]
            if self._flatten_before[i]:
                x = x.reshape(x.shape[0], -1)
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            x, ns = layer.apply(
                params.get(layer.name, {}), net_state.get(layer.name, {}),
                x, training=training, rng=lrng,
            )
            if ns:
                new_state[layer.name] = ns
        return x, new_state

    def _get_step_fn_1f1b(self):
        """The 1F1B pipeline training step: pre-segment vjp + interleaved-
        backward pipeline over the segment + post-segment (head) grads
        accumulated on the last stage — one compiled program.

        vs GPipe (run_pipelined_segment under jax.grad): identical math,
        but the activation stash is a static 2*pipe-1 ring instead of
        O(n_micro), so microbatch count no longer affects HBM.
        Limitations (documented): no masks/TBPTT, and state/aux emitted by
        POST-segment layers inside the per-microbatch loss is discarded
        (plan_sequential_pipeline already keeps such layers out of the
        segment itself)."""
        key = ("train_1f1b",)
        if key not in self._step_fns:
            from jax.sharding import PartitionSpec as P
            from deeplearning4j_tpu.parallel.pipeline import (
                pipeline_train_1f1b,
                split_microbatches,
            )
            from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS as _PA, shard_map

            plan = self._pipeline_plan
            mesh = self._mesh
            n_layers = len(self.conf.layers)
            k, m = plan.k, len(plan.block_names) // plan.k
            cfg = plan.block_config

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def step(params, opt_state, net_state, step_i, features, labels):
                rng = SeedStream.fold(self._stream.root, step_i)
                p_pre = {
                    n: params[n]
                    for n in (l.name for l in self.conf.layers[: plan.start])
                    if n in params
                }
                p_post = {
                    n: params[n]
                    for n in (l.name for l in self.conf.layers[plan.end:])
                    if n in params
                }

                # ---- pre-segment forward; vjp saved for the pipeline's dx
                def f_pre(pp, x):
                    return self._forward_range(
                        pp, net_state, x, 0, plan.start, training=True, rng=rng
                    )

                x1, vjp_pre, st_pre = jax.vjp(f_pre, p_pre, features,
                                              has_aux=True)

                # ---- segment params stacked (k, m, ...), stage-major
                stacked = jax.tree.map(
                    lambda *xs: jnp.stack(xs),
                    *[params[n] for n in plan.block_names],
                )
                stacked = jax.tree.map(
                    lambda a: a.reshape((k, m) + a.shape[1:]), stacked
                )

                @jax.checkpoint
                def stage_fn(sp, h):
                    def body(h, p):
                        y, _ = cfg.apply(p, {}, h, training=True, rng=None)
                        return y, None
                    h, _ = jax.lax.scan(body, h, sp)
                    return h

                x_micro = split_microbatches(x1, plan.n_micro)
                labels_micro = split_microbatches(labels, plan.n_micro)

                def inner(sp, xm, lm):
                    sp_local = jax.tree.map(lambda a: a[0], sp)

                    def loss_grad(y, mi):
                        lbl = lm[mi]

                        def post_loss(pp, yy):
                            out, _ = self._forward_range(
                                pp, net_state, yy, plan.end, n_layers,
                                training=True, rng=rng,
                            )
                            if self._custom_loss is not None:
                                return self._data_loss_custom(
                                    {**pp}, out, lbl, None
                                )
                            if not self._fused_loss:
                                out = self._out_activation(
                                    out.astype(jnp.float32)
                                )
                            return compute_loss(
                                self._loss, out, lbl, None,
                                from_logits=self._fused_loss,
                            )

                        loss_m, (dpost, dy) = jax.value_and_grad(
                            post_loss, argnums=(0, 1)
                        )(p_post, y)
                        return loss_m, dy, dpost

                    return pipeline_train_1f1b(
                        stage_fn, sp_local, xm, loss_grad,
                        axis=_PA,
                    )

                loss, seg_grads, dx_micro, post_grads = shard_map(
                    inner,
                    mesh=mesh,
                    in_specs=(P(_PA), P(), P()),
                    out_specs=(P(), P(_PA), P(), P()),
                    axis_names={_PA},
                    check_vma=False,
                )(stacked, x_micro, labels_micro)

                # ---- assemble the full gradient tree
                dx = dx_micro.reshape((-1,) + dx_micro.shape[2:])
                pre_grads, _dfeat = vjp_pre(dx)
                # shard_map returned (k*m, ...) leaves in block order
                grads = dict(pre_grads)
                for bi, name in enumerate(plan.block_names):
                    grads[name] = jax.tree.map(lambda a, _b=bi: a[_b], seg_grads)
                grads.update(post_grads)
                # regularization is param-local; add its gradient directly
                reg_grads = jax.grad(self._reg_loss)(params)
                grads = jax.tree.map(
                    lambda g, r: g + r.astype(g.dtype), grads, reg_grads
                )
                loss = loss + self._reg_loss(params)

                params, opt_state = self._apply_grads(params, opt_state, grads)
                merged_state = {**net_state, **st_pre}
                return params, opt_state, merged_state, loss

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _run_step_1f1b(self, batch: DataSet) -> None:
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime.crash import oom_report_scope
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        if batch.labels_mask is not None or batch.features_mask is not None:
            raise ValueError(
                "masks are not supported through the 1f1b pipeline schedule; "
                "drop the masks or use schedule='gpipe' without masks"
            )
        step = self._get_step_fn_1f1b()
        with self._observe_step() as obs:
            with oom_report_scope(), active_mesh_scope(self._mesh):
                with obs.phase("host_stage"):
                    feats = place_batch(self, batch.features)
                    labs = place_batch(self, batch.labels, is_label=True)
                with obs.phase("dispatch"):
                    self.params, self.opt_state, self.net_state, loss = step(
                        self.params,
                        self.opt_state,
                        self.net_state,
                        jnp.uint32(self.iteration),
                        feats, labs,
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = batch.num_examples
            self.iteration += 1
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)

    # -- pipeline parallelism ---------------------------------------------
    def _setup_pipeline(self, mesh, n_micro: int = 0,
                        schedule: str = "gpipe") -> None:
        """Called by distribute() when the mesh carries a pipe axis: plan
        which contiguous block run pipelines over it (raises with an
        actionable message when the stack has no pipelineable segment).
        schedule: "gpipe" runs inside the ordinary compiled step via
        _forward; "1f1b" swaps fit() onto a dedicated step whose backward
        is interleaved into the pipeline (O(pipe) activation stash)."""
        from deeplearning4j_tpu.parallel.pipeline import plan_sequential_pipeline
        from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS

        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}; "
                "options: 'gpipe', '1f1b'"
            )
        self._pipeline_plan = plan_sequential_pipeline(
            self.conf.layers, self.params, self._itypes,
            mesh.shape[PIPE_AXIS], n_micro, net_state=self.net_state,
        )
        self._pipeline_schedule = schedule
        self._step_fns.clear()

    def _active_pipeline_plan(self):
        """The plan, iff tracing under a mesh whose pipe axis is real."""
        from deeplearning4j_tpu.runtime.mesh import PIPE_AXIS, active_mesh

        plan = getattr(self, "_pipeline_plan", None)
        if plan is None:
            return None
        mesh = active_mesh()
        if (
            mesh is None
            or PIPE_AXIS not in mesh.axis_names
            or mesh.shape[PIPE_AXIS] < 2
        ):
            return None
        return plan

    def _reg_loss(self, params):
        return regularization_loss(params, [(l.name, l) for l in self.conf.layers])

    def _data_loss_custom(self, p, out, labels, lmask):
        if self._custom_loss_layer is not None:
            return self._custom_loss(
                p.get(self._custom_loss_layer, {}), out, labels, lmask
            )
        return self._custom_loss(out, labels, lmask)

    # -- compiled train step ----------------------------------------------
    def _step_loss(self, p, net_state, feats, labs, *, lmask=None, fmask=None,
                   rng=None, carries=None):
        """The SHARED traced loss body of every training-step program
        (single, TBPTT window, grouped, grouped-TBPTT): forward + data
        loss (custom or enum) + aux + regularization.  Returns
        (loss, new_state, new_carries) — new_carries is {} when carries
        weren't threaded."""
        fwd = self._forward(
            p, net_state, feats, training=True, rng=rng,
            fmask=fmask, carries=carries,
        )
        if carries is not None:
            out, new_state, new_carries = fwd
        else:
            out, new_state = fwd
            new_carries = {}
        if self._custom_loss is not None:
            data_loss = self._data_loss_custom(p, out, labs, lmask)
        else:
            if not self._fused_loss:
                out = self._out_activation(out.astype(jnp.float32))
            data_loss = compute_loss(
                self._loss, out, labs, lmask, from_logits=self._fused_loss
            )
        aux, new_state = pop_aux_losses(new_state)
        return data_loss + self._reg_loss(p) + aux, new_state, new_carries

    # _apply_grads — the shared update epilogue (replicated or ZeRO-1
    # sharded) — lives on the Model base; every builder below calls it.

    def _get_step_fn(self, has_lmask: bool, has_fmask: bool, with_carries: bool,
                     decode=None):
        """The single-batch step program.  With `decode` set (the
        fused-decode fit), the program takes raw bytes and runs the
        lowered transform chain as its first stage — the chain, not
        the batch, produces the masks (sequence padding), and the loss
        body below is shared so fused and host training cannot
        diverge."""
        key = (("train", has_lmask, has_fmask, with_carries)
               if decode is None else ("train_fused", decode.fingerprint))
        key = key + self._step_key_suffix()
        if key not in self._step_fns:

            def core(params, opt_state, net_state, step_i, features,
                     labels, lm, fm, carries):
                rng = SeedStream.fold(self._stream.root, step_i)
                zp = self._zero_placement
                accum = getattr(zp, "accum", 1) if zp is not None else 1
                if accum > 1 and not with_carries:
                    # ZeRO-2 microbatch accumulation: scan over m
                    # microbatches with the grad accumulator SHARDED in
                    # the carry (parallel/zero.py scan_accumulate) — no
                    # full replicated gradient persists across the
                    # accumulation, activation memory drops ~1/m
                    from deeplearning4j_tpu.parallel.zero import (
                        split_accum_microbatches,
                    )

                    micro = split_accum_microbatches(
                        (features, labels, lm, fm), accum
                    )

                    def loss_grad_fn(p, state, arrays, micro_i):
                        f, l, lmm, fmm = arrays
                        # distinct noise per microbatch: dropout et al.
                        # must not repeat the same mask m times
                        rng_i = SeedStream.fold(rng, micro_i)

                        def lf(pp):
                            loss, new_state, _ = self._step_loss(
                                pp, state, f, l, lmask=lmm, fmask=fmm,
                                rng=rng_i, carries=None,
                            )
                            return loss, {**state, **new_state}

                        return jax.value_and_grad(lf, has_aux=True)(p)

                    loss, merged_state, grads = zp.scan_accumulate(
                        loss_grad_fn, params, net_state, micro
                    )
                    params, opt_state = self._apply_grads(
                        params, opt_state, grads
                    )
                    return params, opt_state, merged_state, loss, {}

                def loss_fn(p):
                    loss, new_state, new_carries = self._step_loss(
                        p, net_state, features, labels,
                        lmask=lm, fmask=fm, rng=rng,
                        carries=carries if with_carries else None,
                    )
                    return loss, (new_state, new_carries)

                (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                params, opt_state = self._apply_grads(params, opt_state, grads)
                # carry unchanged state subtrees forward
                merged_state = {**net_state, **new_state}
                return params, opt_state, merged_state, loss, new_carries

            if decode is None:

                @partial(jax.jit, donate_argnums=(0, 1, 2))
                def step(params, opt_state, net_state, step_i, features,
                         labels, lmask, fmask, carries):
                    return core(
                        params, opt_state, net_state, step_i, features,
                        labels,
                        lmask if has_lmask else None,
                        fmask if has_fmask else None,
                        carries,
                    )

            else:
                dec = decode.fn

                @partial(jax.jit, donate_argnums=(0, 1, 2))
                def step(params, opt_state, net_state, step_i, dec_step,
                         raw_feats, raw_labels):
                    # dec_step is the feed's augmentation index (the
                    # batch's _decode_step), NOT model.iteration: the
                    # host fallback folds keys from the same feed
                    # counter, keeping the two paths numerically equal
                    feats, labs, fm, lm = dec(dec_step, raw_feats,
                                              raw_labels)
                    return core(params, opt_state, net_state, step_i,
                                feats, labs, lm, fm, {})

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _fused_decode_reason(self) -> str | None:
        """Why THIS model's fit cannot fuse a device decode, or None.
        The variants with their own step programs (compressed, 1F1B,
        TBPTT) keep host transforms — their programs were not built to
        compose a decode stage."""
        if getattr(self, "_grad_compression", None):
            return "grad-compression fit path"
        if (getattr(self, "_pipeline_schedule", "gpipe") == "1f1b"
                and getattr(self, "_pipeline_plan", None) is not None):
            return "1F1B pipeline fit path"
        if self.conf.backprop_type == "tbptt" and self.conf.tbptt_length > 0:
            return "TBPTT fit path"
        return None

    def _get_step_fn_tbptt(self, has_lmask: bool, has_fmask: bool):
        """Whole-batch TBPTT as ONE compiled XLA program: a lax.scan over
        the time windows, each scan iteration doing grad + updater for its
        window with RNN carries (values only) flowing to the next.  The
        reference runs one fit per window from Java; a per-window jit
        dispatch on a tunneled chip costs more than the window's compute
        (measured ~4ms dispatch vs ~1.4ms compute at BASELINE config 3),
        so the window loop belongs inside the program."""
        key = ("train_tbptt", has_lmask, has_fmask) + self._step_key_suffix()
        if key not in self._step_fns:
            from deeplearning4j_tpu.nn.conf.recurrent import (
                RecurrentLayerConfig,
            )

            L = self.conf.tbptt_length
            rnn_layers = [
                l for l in self.conf.layers
                if isinstance(l, RecurrentLayerConfig)
            ]

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def step(params, opt_state, net_state, step_i, features,
                     labels, lmask, fmask):
                # window + carry setup live INSIDE the program: on a
                # tunneled chip every un-jitted host dispatch costs more
                # than a whole window's compute
                B, T = features.shape[0], features.shape[1]
                W = T // L
                cdtype = (
                    jnp.bfloat16
                    if self._bf16 and jnp.issubdtype(features.dtype, jnp.floating)
                    else features.dtype
                )
                carries = {
                    l.name: l.init_carry(B, cdtype) for l in rnn_layers
                }

                def windowed(a):
                    a = a[:, : W * L].reshape((B, W, L) + a.shape[2:])
                    return jnp.moveaxis(a, 1, 0)

                features_w = windowed(features)
                labels_w = windowed(labels)
                lmask_w = windowed(lmask) if has_lmask else jnp.zeros((W, 0))
                fmask_w = windowed(fmask) if has_fmask else jnp.zeros((W, 0))

                def window(carry, inp):
                    params, opt_state, net_state, carries, si = carry
                    feats, labs, lm, fm = inp
                    rng = SeedStream.fold(self._stream.root, si)

                    def loss_fn(p):
                        loss, new_state, new_carries = self._step_loss(
                            p, net_state, feats, labs,
                            lmask=lm if has_lmask else None,
                            fmask=fm if has_fmask else None,
                            rng=rng, carries=carries,
                        )
                        return loss, (new_state, new_carries)

                    (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params)
                    params, opt_state = self._apply_grads(params, opt_state, grads)
                    merged_state = {**net_state, **new_state}
                    return (
                        (params, opt_state, merged_state, new_carries, si + 1),
                        loss,
                    )

                (params, opt_state, net_state, carries, si), losses = jax.lax.scan(
                    window,
                    (params, opt_state, net_state, carries, step_i),
                    (features_w, labels_w, lmask_w, fmask_w),
                )
                return params, opt_state, net_state, losses, carries, si

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _get_step_fn_tbptt_grouped(self):
        """steps_per_execution x TBPTT composed: an OUTER scan over k
        stacked batches, each iteration running the full window loop with
        freshly-zeroed RNN carries (batch boundaries reset state; window
        boundaries carry it) — k*W optimizer steps, ONE dispatch."""
        key = ("train_tbptt_grouped",) + self._step_key_suffix()
        if key not in self._step_fns:
            from deeplearning4j_tpu.nn.conf.recurrent import (
                RecurrentLayerConfig,
            )

            L = self.conf.tbptt_length
            rnn_layers = [
                l for l in self.conf.layers
                if isinstance(l, RecurrentLayerConfig)
            ]

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def step(params, opt_state, net_state, step_i, features_k, labels_k):
                B, T = features_k.shape[1], features_k.shape[2]
                W = T // L
                cdtype = (
                    jnp.bfloat16
                    if self._bf16
                    and jnp.issubdtype(features_k.dtype, jnp.floating)
                    else features_k.dtype
                )

                def windowed(a):
                    a = a[:, : W * L].reshape((B, W, L) + a.shape[2:])
                    return jnp.moveaxis(a, 1, 0)

                def one_batch(carry, inp):
                    params, opt_state, net_state, si = carry
                    feats, labs = inp
                    carries = {
                        l.name: l.init_carry(B, cdtype) for l in rnn_layers
                    }

                    def window(c, winp):
                        params, opt_state, net_state, carries, si = c
                        wf, wl = winp
                        rng = SeedStream.fold(self._stream.root, si)

                        def loss_fn(p):
                            loss, new_state, new_carries = self._step_loss(
                                p, net_state, wf, wl, rng=rng, carries=carries
                            )
                            return loss, (new_state, new_carries)

                        (loss, (new_state, new_carries)), grads = (
                            jax.value_and_grad(loss_fn, has_aux=True)(params)
                        )
                        params, opt_state = self._apply_grads(
                            params, opt_state, grads
                        )
                        merged = {**net_state, **new_state}
                        return (
                            (params, opt_state, merged, new_carries, si + 1),
                            loss,
                        )

                    (params, opt_state, net_state, _, si), losses = (
                        jax.lax.scan(
                            window,
                            (params, opt_state, net_state, carries, si),
                            (windowed(feats), windowed(labs)),
                        )
                    )
                    return (params, opt_state, net_state, si), losses

                (params, opt_state, net_state, si), losses = jax.lax.scan(
                    one_batch,
                    (params, opt_state, net_state, step_i),
                    (features_k, labels_k),
                )
                return params, opt_state, net_state, losses.reshape(-1), si

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    # -- compressed-gradient DP step (int8 allreduce over the data axis) ---
    def _setup_grad_compression(self, mesh) -> None:
        """Called by distribute(ParallelConfig(grad_compression="int8")):
        switch fit() to the shard_map step that exchanges gradients as
        error-feedback int8 (parallel/compression.py).  The residual
        carries one slot per data shard (leading dim sharded on the data
        axis)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.runtime.mesh import DATA_AXIS

        n = mesh.shape[DATA_AXIS]
        if n < 2:
            return
        self._grad_compression = "int8"
        self._grad_residual = jax.device_put(
            jax.tree.map(
                lambda p: jnp.zeros((n,) + p.shape, p.dtype), self.params
            ),
            NamedSharding(mesh, P(DATA_AXIS)),
        )
        self._step_fns.clear()

    def _get_step_fn_compressed(self, has_lmask: bool, has_fmask: bool):
        key = ("train_q", has_lmask, has_fmask)
        if key not in self._step_fns:
            from jax.sharding import PartitionSpec as P
            from deeplearning4j_tpu.parallel.compression import (
                quantized_allreduce_tree,
            )
            from deeplearning4j_tpu.runtime.mesh import DATA_AXIS, shard_map

            mesh = self._mesh

            def shard_body(params, opt_state, net_state, resid, step_i,
                           features, labels, lmask, fmask):
                rng = SeedStream.fold(self._stream.root, step_i)
                # per-shard dropout streams (each shard sees different data)
                rng = jax.random.fold_in(rng, jax.lax.axis_index(DATA_AXIS))

                def loss_fn(p):
                    out, new_state = self._forward(
                        p, net_state, features, training=True, rng=rng,
                        fmask=fmask if has_fmask else None,
                    )
                    if self._custom_loss is not None:
                        data_loss = self._data_loss_custom(
                            p, out, labels, lmask if has_lmask else None
                        )
                    else:
                        if not self._fused_loss:
                            out = self._out_activation(out.astype(jnp.float32))
                        data_loss = compute_loss(
                            self._loss, out, labels,
                            lmask if has_lmask else None,
                            from_logits=self._fused_loss,
                        )
                    aux, new_state = pop_aux_losses(new_state)
                    return data_loss + self._reg_loss(p) + aux, new_state

                (loss, new_state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                resid_local = jax.tree.map(lambda a: a[0], resid)
                grads, resid_local = quantized_allreduce_tree(
                    grads, resid_local, axis=DATA_AXIS,
                    key=jax.random.fold_in(rng, 0x51),
                )
                loss = jax.lax.pmean(loss, DATA_AXIS)
                new_state = jax.tree.map(
                    lambda a: jax.lax.pmean(a, DATA_AXIS), new_state
                )
                updates, new_opt = self._tx.update(grads, opt_state, params)
                params = jax.tree.map(
                    lambda p, u: p + u.astype(p.dtype), params, updates
                )
                merged = {**net_state, **new_state}
                resid = jax.tree.map(lambda a: a[None], resid_local)
                return params, new_opt, merged, resid, loss

            @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
            def step(params, opt_state, net_state, resid, step_i,
                     features, labels, lmask, fmask):
                return shard_map(
                    shard_body,
                    mesh=mesh,
                    in_specs=(P(), P(), P(), P(DATA_AXIS), P(),
                              P(DATA_AXIS), P(DATA_AXIS),
                              P(DATA_AXIS) if has_lmask else P(),
                              P(DATA_AXIS) if has_fmask else P()),
                    out_specs=(P(), P(), P(), P(DATA_AXIS), P()),
                    check_vma=False,
                )(params, opt_state, net_state, resid, step_i,
                  features, labels, lmask, fmask)

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _run_step_compressed(self, batch: DataSet):
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime.crash import oom_report_scope
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        has_lmask = batch.labels_mask is not None
        has_fmask = batch.features_mask is not None
        step = self._get_step_fn_compressed(has_lmask, has_fmask)
        empty = np.zeros((0,), np.float32)
        with self._observe_step() as obs:
            with oom_report_scope(), active_mesh_scope(self._mesh):
                with obs.phase("host_stage"):
                    feats = place_batch(self, batch.features)
                    labs = place_batch(self, batch.labels, is_label=True)
                    lm = (place_batch(self, batch.labels_mask, is_mask=True)
                          if has_lmask else empty)
                    fm = (place_batch(self, batch.features_mask, is_mask=True)
                          if has_fmask else empty)
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state,
                     self._grad_residual, loss) = step(
                        self.params,
                        self.opt_state,
                        self.net_state,
                        self._grad_residual,
                        jnp.uint32(self.iteration),
                        feats, labs, lm, fm,
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = batch.num_examples
            self.iteration += 1
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)

    def fit(self, data, epochs: int = 1, batch_size: int | None = None,
            steps_per_execution: int = 1) -> None:
        """steps_per_execution > 1 runs that many optimizer steps as ONE
        compiled XLA program (a lax.scan over stacked batches) — the
        tf.keras steps_per_execution knob.  On a TPU whose per-dispatch
        latency rivals a small model's step time this is the difference
        between dispatch-bound and compute-bound training.  TBPTT models
        compose: k batches' full window loops run in one program (RNN
        carries reset at batch boundaries).  Ragged/mismatched batches and
        the compressed / 1F1B-pipelined / distributed paths fall back to
        per-batch stepping (they have their own step programs).

        Listener caveat (shared with Keras): per-iteration listeners fire
        AFTER each group completes, so a state-READING listener
        (checkpoint/evaluative) invoked for a mid-group iteration sees the
        END-of-group params; losses/scores are exact per step.  Keep
        steps_per_execution=1 when mid-group snapshots must be exact."""
        if self.params is None:
            self.init()
        iterator = _as_iterator(data, batch_size)
        self._donation_checked = False     # re-arm the one-time alias check
        self._ensure_watchdog()            # step-deadline hang detection
        use_multi = (
            steps_per_execution > 1
            and not getattr(self, "_grad_compression", None)
            and getattr(self, "_pipeline_schedule", "gpipe") != "1f1b"
            and getattr(self, "_batch_sharding", None) is None
        )
        # device-compiled data pipeline: an iterator advertising a
        # lowerable transform chain feeds RAW bytes and the chain runs
        # inside the step program (datavec/device.py); unsupported fit
        # variants and non-lowerable chains keep host transforms
        feed_src, decode = self._device_decode_feed(
            iterator, self._fused_decode_reason()
        )
        self._device_decode = decode
        # software pipelining: batch N+1 is pulled + staged to device on
        # a background thread while step N computes (flags.prefetch_depth
        # deep; 0 = serial).  close() in the finally stops the producer
        # even when a step raises mid-epoch.
        feed = self._prefetch_feed(feed_src)
        try:
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch)
                if use_multi:
                    self._fit_epoch_multi(feed, steps_per_execution)
                else:
                    for batch in self._timed_batches(feed):
                        self._fit_one(batch)
                for lst in self.listeners:
                    lst.on_epoch_end(self, self.epoch)
                self.epoch += 1
                iterator.reset()
        finally:
            self._device_decode = None
            if feed is not feed_src:
                feed.close()
        for lst in self.listeners:
            # getattr: on_fit_end is newer than the SPI — tolerate
            # duck-typed listeners written against the original three hooks
            getattr(lst, "on_fit_end", lambda m: None)(self)

    def _fit_epoch_multi(self, iterator, spe: int) -> None:
        def group_ok(buf):
            f0, l0 = buf[0].features, buf[0].labels
            # raw-tag uniformity: a group mixing raw-tagged and
            # host-decoded batches must degrade to the per-batch path
            # (which routes tags correctly) — the grouped program would
            # stack the tagged batches' undecoded bytes into the loss
            raw0 = bool(getattr(buf[0], "_raw_for_device_decode", False))
            return all(
                b.features.shape == f0.shape
                and b.labels.shape == l0.shape
                and b.features_mask is None
                and b.labels_mask is None
                and bool(getattr(b, "_raw_for_device_decode", False)) == raw0
                for b in buf
            )

        # the device-resident step counter is only valid while EVERY step
        # goes through the grouped program; any single-step fallback (or
        # steps taken before this fit) advances self.iteration outside it
        tbptt = (
            self.conf.backprop_type == "tbptt" and self.conf.tbptt_length > 0
        )

        def flush(buf):
            if not group_ok(buf):
                for b in buf:
                    self._fit_one(b)
                self._multi_iter_dev = None
                return
            if tbptt:
                T = buf[0].features.shape[1]
                if T % self.conf.tbptt_length or not getattr(
                    self, "_tbptt_scan", True
                ):
                    # no remainder-window leg in the grouped program, and
                    # _tbptt_scan=False (the scan-miscompile escape hatch)
                    # must keep forcing the per-window path
                    for b in buf:
                        self._fit_one(b)
                    self._multi_iter_dev = None
                    return
                self._fit_group(buf, self._run_steps_grouped_tbptt)
            else:
                self._fit_group(buf, self._run_steps_grouped)

        self._multi_iter_dev = None
        buf: list[DataSet] = []
        for batch in self._timed_batches(iterator):
            buf.append(batch)
            if len(buf) == spe:
                flush(buf)
                buf = []
        for b in buf:                       # ragged tail group
            self._fit_one(b)
            self._multi_iter_dev = None

    def _get_step_fn_multi(self, decode=None):
        """k optimizer steps in one program: lax.scan over the stacked
        batch axis, same body as the single step.  With `decode` set,
        each scan iteration runs the lowered transform chain first —
        raw stacked bytes in, k losses out."""
        key = (("train_multi",) if decode is None
               else ("train_multi_fused", decode.fingerprint))
        key = key + self._step_key_suffix()
        if key not in self._step_fns:
            dec = None if decode is None else decode.fn

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def step(params, opt_state, net_state, step_i, features_k,
                     labels_k, dec_steps_k=None):
                def one(carry, inp):
                    params, opt_state, net_state, si = carry
                    fmask = lmask = None
                    if dec is not None:
                        # per-batch feed augmentation indices, not si:
                        # see _get_step_fn's fused signature
                        feats, labs, ds = inp
                        feats, labs, fmask, lmask = dec(ds, feats, labs)
                    else:
                        feats, labs = inp
                    rng = SeedStream.fold(self._stream.root, si)

                    def loss_fn(p):
                        loss, new_state, _ = self._step_loss(
                            p, net_state, feats, labs,
                            lmask=lmask, fmask=fmask, rng=rng,
                        )
                        return loss, new_state

                    (loss, new_state), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params)
                    params, opt_state = self._apply_grads(params, opt_state, grads)
                    merged = {**net_state, **new_state}
                    return (params, opt_state, merged, si + 1), loss

                xs = ((features_k, labels_k) if dec is None
                      else (features_k, labels_k, dec_steps_k))
                (params, opt_state, net_state, si), losses = jax.lax.scan(
                    one,
                    (params, opt_state, net_state, step_i),
                    xs,
                )
                return params, opt_state, net_state, losses, si

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _run_steps_grouped_tbptt(self, batches: list) -> None:
        from deeplearning4j_tpu.nn.conf.recurrent import Bidirectional
        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        # same config-level preconditions the per-batch TBPTT path raises on
        if self.conf.output_type().kind != "rnn":
            raise ValueError(
                "TBPTT requires a per-timestep output (RnnOutputLayer)"
            )
        if any(isinstance(l, Bidirectional) for l in self.conf.layers):
            raise ValueError("TBPTT is undefined for bidirectional networks")
        T = batches[0].features.shape[1]
        if batches[0].labels.ndim < 2 or batches[0].labels.shape[1] != T:
            raise ValueError(
                "TBPTT needs per-timestep labels with a (B, T, ...) time axis"
            )
        step = self._get_step_fn_tbptt_grouped()
        k = len(batches)
        W = T // self.conf.tbptt_length
        with self._observe_step(k * W) as obs:
            with oom_report_scope():
                with obs.phase("host_stage"):
                    feats = jnp.stack(
                        [jnp.asarray(b.features) for b in batches]
                    )
                    labs = jnp.stack([jnp.asarray(b.labels) for b in batches])
                    if getattr(self, "_multi_iter_dev", None) is None:
                        self._multi_iter_dev = jax.device_put(
                            np.uint32(self.iteration)
                        )
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state, losses,
                     self._multi_iter_dev) = step(
                        self.params, self.opt_state, self.net_state,
                        self._multi_iter_dev, feats, labs,
                    )
                with obs.phase("device_sync"):
                    obs.sync(losses)
            self.last_batch_size = batches[-1].num_examples
            self._finish_grouped_steps(losses, k * W)
        # the per-batch TBPTT path keeps its own device counter; resync
        self._tbptt_iter_dev = None

    def _run_steps_grouped(self, batches: list) -> None:
        from deeplearning4j_tpu.runtime import faults
        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        decode = self._device_decode if (
            self._device_decode is not None
            and all(getattr(b, "_raw_for_device_decode", False)
                    for b in batches)
        ) else None
        step = self._get_step_fn_multi(decode)
        k = len(batches)
        with self._observe_step(k) as obs:
            with oom_report_scope():
                with obs.phase("host_stage"):
                    extra = ()
                    if decode is not None:
                        # fused-decode host boundary (see _run_step_fused)
                        faults.maybe_fail("data.device_decode")
                        extra = (jnp.asarray(
                            [getattr(b, "_decode_step", self.iteration + i)
                             for i, b in enumerate(batches)], jnp.uint32,
                        ),)
                    feats = jnp.stack(
                        [jnp.asarray(b.features) for b in batches]
                    )
                    labs = jnp.stack([jnp.asarray(b.labels) for b in batches])
                    if getattr(self, "_multi_iter_dev", None) is None:
                        self._multi_iter_dev = jax.device_put(
                            np.uint32(self.iteration)
                        )
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state, losses,
                     self._multi_iter_dev) = step(
                        self.params, self.opt_state, self.net_state,
                        self._multi_iter_dev, feats, labs, *extra,
                    )
                with obs.phase("device_sync"):
                    obs.sync(losses)
            self.last_batch_size = batches[-1].num_examples
            if decode is not None:
                self._count_device_decode(
                    decode, batches[0].features, batches[0].labels, k=k
                )
            # listeners span lives in _finish_grouped_steps
            self._finish_grouped_steps(losses, k)

    def fit_batch(self, batch: DataSet) -> None:
        if self.params is None:
            self.init()
        if getattr(self, "_grad_compression", None):
            if self.conf.backprop_type == "tbptt" and self.conf.tbptt_length > 0:
                raise ValueError(
                    "grad_compression does not compose with TBPTT "
                    "(per-window carries cross the compressed-sync "
                    "boundary); use standard backprop or drop compression"
                )
            self._run_step_compressed(batch)
            return
        if (
            getattr(self, "_pipeline_schedule", "gpipe") == "1f1b"
            and getattr(self, "_pipeline_plan", None) is not None
            and getattr(self, "_mesh", None) is not None
        ):
            # NOT _active_pipeline_plan(): that checks the ambient mesh
            # scope, which only exists INSIDE a running step — at routing
            # time it would always be None and 1F1B would silently fall
            # back to GPipe
            self._run_step_1f1b(batch)
            return
        if self.conf.backprop_type == "tbptt" and self.conf.tbptt_length > 0:
            self._fit_batch_tbptt(batch)
            return
        self._run_step(batch, carries=None)

    def _run_step(self, batch: DataSet, carries):
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        if (self._device_decode is not None and carries is None
                and getattr(batch, "_raw_for_device_decode", False)):
            if batch.features_mask is None and batch.labels_mask is None:
                return self._run_step_fused(batch, self._device_decode)
            # a raw batch carrying its OWN masks: the fused program
            # cannot see them (it stages features/labels only), so
            # decode on the host — masks thread through the chain —
            # and fall through to the normal masked step.  (_RawFeed
            # host-decodes masked batches itself; this is the defensive
            # net for hand-tagged batches.)
            batch = self._device_decode.host(
                getattr(batch, "_decode_step", self.iteration), batch
            )
        has_lmask = batch.labels_mask is not None
        has_fmask = batch.features_mask is not None
        with_carries = carries is not None
        step = self._get_step_fn(has_lmask, has_fmask, with_carries)
        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        empty = np.zeros((0,), np.float32)
        with self._observe_step() as obs:
            # staging stays INSIDE the oom/mesh scopes (a device OOM while
            # placing the batch must still write the crash report)
            with oom_report_scope(), active_mesh_scope(
                getattr(self, "_mesh", None)
            ):
                with obs.phase("host_stage"):
                    feats = place_batch(self, batch.features)
                    labs = place_batch(self, batch.labels, is_label=True)
                    lm = (place_batch(self, batch.labels_mask, is_mask=True)
                          if has_lmask else empty)
                    fm = (place_batch(self, batch.features_mask, is_mask=True)
                          if has_fmask else empty)
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state, loss,
                     new_carries) = step(
                        self.params,
                        self.opt_state,
                        self.net_state,
                        jnp.uint32(self.iteration),
                        feats, labs, lm, fm,
                        carries if with_carries else {},
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = batch.num_examples
            self.iteration += 1
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)
        return new_carries

    def _run_step_fused(self, batch: DataSet, decode) -> None:
        """Dispatch one fused decode+train program over a raw batch:
        the host stages undecoded bytes (smaller or cheaper transfers,
        zero per-batch transform work) and the chain runs as the first
        stage of the compiled step."""
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime import faults
        from deeplearning4j_tpu.runtime.crash import oom_report_scope
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        step = self._get_step_fn(False, False, False, decode)
        with self._observe_step() as obs:
            with oom_report_scope(), active_mesh_scope(
                getattr(self, "_mesh", None)
            ):
                with obs.phase("host_stage"):
                    # fault site: the fused-decode host boundary (armed
                    # plans provoke decode-stage failures; disarmed this
                    # is one attribute check)
                    faults.maybe_fail("data.device_decode")
                    feats = place_batch(self, batch.features)
                    labs = place_batch(self, batch.labels, is_label=True)
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state,
                     loss, _) = step(
                        self.params, self.opt_state, self.net_state,
                        jnp.uint32(self.iteration),
                        jnp.uint32(getattr(batch, "_decode_step",
                                           self.iteration)),
                        feats, labs,
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = batch.num_examples
            self.iteration += 1
            self._count_device_decode(decode, feats, labs)
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)

    def _fit_batch_tbptt(self, batch: DataSet) -> None:
        """Truncated BPTT: split the time axis into tbptt_length windows;
        gradients are confined to each window, RNN carries flow across
        windows (values only — the window boundary stops the gradient,
        matching BackpropType.TruncatedBPTT)."""
        from deeplearning4j_tpu.nn.conf.recurrent import Bidirectional

        T = batch.features.shape[1]
        L = self.conf.tbptt_length
        if self.conf.output_type().kind != "rnn":
            raise ValueError(
                "TBPTT requires a per-timestep output (RnnOutputLayer); this "
                "network collapses the time axis — use standard backprop"
            )
        if any(isinstance(l, Bidirectional) for l in self.conf.layers):
            raise ValueError(
                "TBPTT is undefined for bidirectional networks (the backward "
                "direction crosses window boundaries) — use standard backprop"
            )
        if batch.labels.ndim < 2 or batch.labels.shape[1] != T:
            raise ValueError(
                "TBPTT needs per-timestep labels with a (B, T, ...) time "
                f"axis matching features; got {batch.labels.shape} for T={T}"
            )
        W, rem = divmod(T, L)
        if (
            not getattr(self, "_tbptt_scan", True)
            or getattr(self, "_batch_sharding", None) is not None
            or W < 2
        ):
            # distributed models keep the per-window path (place_batch
            # shards axis 0; the scanned layout's leading axis is windows)
            carries: dict = {}
            for t0 in range(0, T, L):
                sl = slice(t0, min(t0 + L, T))
                window = DataSet(
                    batch.features[:, sl],
                    batch.labels[:, sl],
                    None if batch.features_mask is None else batch.features_mask[:, sl],
                    None if batch.labels_mask is None else batch.labels_mask[:, sl],
                )
                carries = self._run_step(window, carries=carries)
            return

        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        has_lmask = batch.labels_mask is not None
        has_fmask = batch.features_mask is not None
        step = self._get_step_fn_tbptt(has_lmask, has_fmask)
        # device-resident step counter + cached empty: a tunneled chip pays
        # milliseconds per host->device transfer, so per-call traffic is
        # held to the batch handles alone
        with self._observe_step(W) as obs:
            with oom_report_scope():
                with obs.phase("host_stage"):
                    if getattr(self, "_tbptt_iter_dev", None) is None:
                        self._tbptt_iter_dev = jax.device_put(
                            np.uint32(self.iteration)
                        )
                        self._empty_dev = jax.device_put(
                            np.zeros((0,), np.float32)
                        )
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state, losses,
                     carries, self._tbptt_iter_dev) = step(
                        self.params,
                        self.opt_state,
                        self.net_state,
                        self._tbptt_iter_dev,
                        batch.features,
                        batch.labels,
                        batch.labels_mask if has_lmask else self._empty_dev,
                        batch.features_mask if has_fmask else self._empty_dev,
                    )
                with obs.phase("device_sync"):
                    obs.sync(losses)
            self.last_batch_size = batch.num_examples
            self._finish_grouped_steps(losses, W)
        if rem:
            tail = slice(W * L, T)
            window = DataSet(
                batch.features[:, tail],
                batch.labels[:, tail],
                None if batch.features_mask is None else batch.features_mask[:, tail],
                None if batch.labels_mask is None else batch.labels_mask[:, tail],
            )
            self._run_step(window, carries=carries)
            # the tail step advanced self.iteration outside the device
            # counter; resync on the next batch
            self._tbptt_iter_dev = None

    # -- layerwise unsupervised pretraining --------------------------------
    def pretrain(self, data, epochs: int = 1, batch_size: int | None = None) -> None:
        """Greedy layerwise unsupervised pretraining (reference
        MultiLayerNetwork.pretrain(DataSetIterator)): every PRETRAINABLE
        layer (AutoEncoder / VariationalAutoencoder) is trained in stack
        order on the features only."""
        for i, layer in enumerate(self.conf.layers):
            if getattr(layer, "PRETRAINABLE", False):
                self.pretrain_layer(i, data, epochs=epochs, batch_size=batch_size)

    def pretrain_layer(
        self, index: int, data, epochs: int = 1, batch_size: int | None = None
    ) -> float:
        """Unsupervised pretraining of one layer (reference
        MultiLayerNetwork.pretrainLayer): the frozen prefix runs in
        inference mode, then (prefix-forward -> pretrain_loss -> grad ->
        updater) for THIS layer's params compiles into one donated-buffer
        XLA step.  Returns the last pretrain loss."""
        if self.params is None:
            self.init()
        layer = self.conf.layers[index]
        if not getattr(layer, "PRETRAINABLE", False):
            raise ValueError(
                f"layer {index} ({type(layer).__name__}) is not pretrainable; "
                "only AutoEncoder/VariationalAutoencoder layers support "
                "unsupervised pretraining"
            )
        tx = with_gradient_clipping(
            self.conf.updater.to_optax(self.conf.steps_per_epoch),
            self.conf.gradient_clip_value,
            self.conf.gradient_clip_norm,
        )
        opt_state = tx.init(self.params[layer.name])
        frozen_params = {
            k: v for k, v in self.params.items() if k != layer.name
        }

        @partial(jax.jit, donate_argnums=(0, 1))
        def pstep(lp, opt_state, frozen, step_i, features):
            rng = SeedStream.fold(self._stream.root, step_i)

            def loss_fn(lp):
                x = self._prefix_forward(frozen, features, index)
                return layer.pretrain_loss(lp, jax.lax.stop_gradient(x), rng)

            loss, grads = jax.value_and_grad(loss_fn)(lp)
            updates, opt_state = tx.update(grads, opt_state, lp)
            lp = jax.tree.map(lambda p, u: p + u.astype(p.dtype), lp, updates)
            return lp, opt_state, loss

        iterator = _as_iterator(data, batch_size)
        lp = self.params.pop(layer.name)
        loss = float("nan")
        step_i = 0
        try:
            for _ in range(epochs):
                for batch in iterator:
                    lp, opt_state, loss = pstep(
                        lp, opt_state, frozen_params, jnp.uint32(step_i),
                        jnp.asarray(batch.features),
                    )
                    step_i += 1
                iterator.reset()
        finally:
            self.params[layer.name] = lp
        return float(loss)

    def _prefix_forward(self, params, x, stop: int):
        """Inference-mode forward through layers [0, stop) — the pretrain
        prefix.  Pure/traced; BN etc. use stored state without updating."""
        x = entry_cast(x, self._bf16)
        for i, layer in enumerate(self.conf.layers[:stop]):
            if self._flatten_before[i]:
                x = x.reshape(x.shape[0], -1)
            lp = params.get(layer.name, {})
            ls = self.net_state.get(layer.name, {})
            x, _ = layer.apply(lp, ls, x, training=False, rng=None)
        if self._flatten_before[stop]:
            x = x.reshape(x.shape[0], -1)
        return x.astype(jnp.float32)

    # -- inference ---------------------------------------------------------
    def _get_infer_fn(self, has_fmask: bool = False):
        key = ("infer", has_fmask) + self._step_key_suffix()
        if key not in self._step_fns:

            @jax.jit
            def infer(params, net_state, features, fmask):
                out, _ = self._forward(
                    params,
                    net_state,
                    features,
                    training=False,
                    rng=None,
                    fmask=fmask if has_fmask else None,
                )
                return self._out_activation(out.astype(jnp.float32))

            self._step_fns[key] = self._register_program(key, infer)
        return self._step_fns[key]

    def output(self, features, features_mask=None) -> jax.Array:
        """Forward pass with the output activation applied (reference
        `MultiLayerNetwork.output()`)."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        has_fmask = features_mask is not None
        with active_mesh_scope(getattr(self, "_mesh", None)):
            return self._get_infer_fn(has_fmask)(
                self.params,
                self.net_state,
                features,
                features_mask if has_fmask else np.zeros((0,), np.float32),
            )

    # -- stateful streaming inference (rnnTimeStep role) -------------------
    def _init_carries(self, batch: int) -> dict:
        from deeplearning4j_tpu.nn.conf.recurrent import RecurrentLayerConfig

        dtype = jnp.bfloat16 if self._bf16 else jnp.float32
        return {
            l.name: l.init_carry(batch, dtype)
            for l in self.conf.layers
            if isinstance(l, RecurrentLayerConfig)
        }

    def rnn_time_step(self, features) -> jax.Array:
        """Streaming RNN inference: feed a chunk (B, T, F), carry hidden
        state to the next call (the reference's rnnTimeStep).  Output
        activation applied.  Jitted (cached per chunk shape) so
        token-by-token generation loops stay fast."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.nn.conf.recurrent import Bidirectional

        if any(isinstance(l, Bidirectional) for l in self.conf.layers):
            raise ValueError(
                "rnn_time_step is undefined for bidirectional networks (the "
                "backward pass needs the full future sequence) — use output()"
            )
        if not getattr(self, "_rnn_stream_state", None):
            self._rnn_stream_state = self._init_carries(features.shape[0])
        key = ("rnn_step",) + self._step_key_suffix()
        if key not in self._step_fns:

            @jax.jit
            def rnn_step(params, net_state, x, carries):
                out, _, new_carries = self._forward(
                    params, net_state, x, training=False, rng=None, carries=carries
                )
                return self._out_activation(out.astype(jnp.float32)), new_carries

            self._step_fns[key] = self._register_program(key, rnn_step)
        out, self._rnn_stream_state = self._step_fns[key](
            self.params, self.net_state, jnp.asarray(features), self._rnn_stream_state
        )
        return out

    def rnn_clear_previous_state(self) -> None:
        self._rnn_stream_state = {}

    def predict(self, features) -> np.ndarray:
        """Argmax class predictions (reference `predict()`)."""
        return np.asarray(jnp.argmax(self.output(features), axis=-1))

    def feed_forward(self, features) -> list[jax.Array]:
        """Per-layer activations (reference `feedForward()`); not jitted —
        debugging/inspection path."""
        acts = []
        x = jnp.asarray(features)
        x = entry_cast(x, self._bf16)
        for i, layer in enumerate(self.conf.layers):
            if self._flatten_before[i]:
                x = x.reshape(x.shape[0], -1)
            lp = self.params.get(layer.name, {})
            ls = self.net_state.get(layer.name, {})
            x, _ = layer.apply(lp, ls, x, training=False, rng=None)
            acts.append(x)
        return acts

    def score(self, ds: DataSet) -> float:
        """Loss (incl. regularization) on a dataset without updating."""
        out, _ = self._forward(
            self.params,
            self.net_state,
            jnp.asarray(ds.features),
            training=False,
            rng=None,
            fmask=ds.features_mask,
        )
        if self._custom_loss is not None:
            loss = self._data_loss_custom(
                self.params, out, jnp.asarray(ds.labels), ds.labels_mask
            )
        else:
            if not self._fused_loss:
                out = self._out_activation(out.astype(jnp.float32))
            loss = compute_loss(
                self._loss, out, jnp.asarray(ds.labels), ds.labels_mask,
                from_logits=self._fused_loss,
            )
        return float(loss + self._reg_loss(self.params))

    def evaluate(self, data, batch_size: int | None = None):
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation

        iterator = _as_iterator(data, batch_size)
        ev = Evaluation()
        last = self.conf.layers[-1]
        for batch in iterator:
            probs = self.output(batch.features, batch.features_mask)
            if hasattr(last, "evaluation_output"):
                # custom heads (CenterLoss concat, ChunkedSoftmax hidden
                # states) need their logits extracted — a raw argmax over
                # apply()'s output would be garbage
                probs = last.evaluation_output(
                    self.params.get(last.name, {}), probs
                )
            labels = batch.labels
            parr = np.asarray(probs)
            larr = np.asarray(labels)
            n_out = parr.shape[-1]
            # int class ids (the chunked head's label form) are detected by
            # ELEMENT COUNT — one label per prediction position — exactly
            # as ChunkedSoftmaxOutputLayer's loss does; a trailing-dim
            # comparison would misread (B,T) ids as one-hot whenever
            # T == n_out
            if larr.ndim >= 1 and n_out > 1 and larr.size * n_out == parr.size:
                ids = larr.astype(np.int64)
                if ids.ndim == parr.ndim and ids.shape[-1] == 1:
                    ids = ids[..., 0]
                # build the one-hot batch directly — np.eye(vocab) would be
                # a vocab^2 identity for exactly the large-vocab case
                onehot = np.zeros(ids.shape + (n_out,), np.float32)
                np.put_along_axis(onehot, ids[..., None], 1.0, axis=-1)
                labels = onehot
            ev.eval(labels, np.asarray(probs), mask=batch.labels_mask)
        return ev

    # -- serialization helpers --------------------------------------------
    def clone(self) -> "SequentialModel":
        m = SequentialModel(self.conf)
        if self.params is not None:
            m.params = jax.tree.map(jnp.copy, self.params)
            m.net_state = jax.tree.map(jnp.copy, self.net_state)
            m.opt_state = jax.tree.map(jnp.copy, self.opt_state)
        return m
