"""GraphModel — the ComputationGraph role, compiled whole-step.

The reference walks GraphVertex[] in topological order per minibatch with
per-vertex workspaces (SURVEY.md §3.2).  Here the topological walk happens
once at TRACE time; the training iteration over the whole DAG — all
branches, merges, skip connections, multiple outputs — is one compiled XLA
computation with donated buffers, exactly like SequentialModel.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.models._cast import entry_cast
from deeplearning4j_tpu.models.model import Model
from deeplearning4j_tpu.models._common import (
    mask_frozen_tx,
    pop_aux_losses,
    regularization_loss,
    resolve_output_spec,
)
from deeplearning4j_tpu.nn.conf.graph_conf import GraphConfiguration
from deeplearning4j_tpu.nn.losses import compute as compute_loss
from deeplearning4j_tpu.nn.updaters import with_gradient_clipping
from deeplearning4j_tpu.runtime.backend import backend
from deeplearning4j_tpu.runtime.rng import SeedStream


class GraphModel(Model):
    def __init__(self, conf: GraphConfiguration):
        super().__init__()
        self.conf = conf
        self._topo = conf.topological_order()
        self._types, self._flatten = conf.infer_types()
        self._out_specs = self._resolve_outputs()
        self._bf16 = (
            conf.bf16_compute if conf.bf16_compute is not None else backend().is_tpu
        )
        self._tx = with_gradient_clipping(
            conf.updater.to_optax(conf.steps_per_epoch),
            conf.gradient_clip_value,
            conf.gradient_clip_norm,
        )
        self._tx = self._mask_frozen(self._tx)
        self._stream = SeedStream(conf.seed)
        self._step_fns: dict[Any, Any] = {}
        self._infer_fn = None

    # -- construction ------------------------------------------------------
    def _resolve_outputs(self):
        """(loss, activation, fused, custom_loss_fn) per network output,
        in declared order.  custom_loss_fn is set for layers that carry
        their own loss (e.g. Yolo2OutputLayer) and bypasses the enum path."""
        from deeplearning4j_tpu.nn.activations import Activation
        from deeplearning4j_tpu.nn.losses import Loss

        by_name = {n.name: n for n in self.conf.nodes}
        specs = []
        for out in self.conf.network_outputs:
            layer = by_name[out].layer
            if layer is not None and hasattr(layer, "compute_loss_with_params"):
                # params-aware custom loss (CenterLossOutputLayer): loss
                # sites call spec[3] as fn(node_params, out, labels, mask)
                specs.append((
                    Loss.MSE, Activation.IDENTITY, False,
                    ("with_params", out, layer.compute_loss_with_params),
                ))
                continue
            if layer is not None and hasattr(layer, "compute_loss"):
                specs.append((Loss.MSE, Activation.IDENTITY, False, layer.compute_loss))
                continue
            if layer is None or not hasattr(layer, "loss"):
                raise ValueError(
                    f"network output {out!r} must be an OutputLayer/"
                    "RnnOutputLayer/LossLayer"
                )
            specs.append(resolve_output_spec(layer) + (None,))
        return specs

    def _mask_frozen(self, tx):
        return mask_frozen_tx(
            tx,
            {n.name for n in self.conf.nodes if n.layer is not None and n.layer.frozen},
        )

    def _layer_itype(self, node):
        """Post-flatten input type for a layer node, from the cached walk."""
        t = self._types[node.inputs[0]]
        if self._flatten[node.name]:
            from deeplearning4j_tpu.nn.conf.input_type import InputType

            t = InputType.feed_forward(t.flat_size)
        return t

    def init(self) -> "GraphModel":
        params, state = {}, {}
        for node in self._topo:
            if node.pkey in params or node.pkey in state:
                continue   # shared param_key: first call initializes
            if node.layer is None:
                if node.vertex.HAS_PARAMS:
                    itypes = [self._types[i] for i in node.inputs]
                    p = node.vertex.init(
                        self._stream.key(f"init/{node.pkey}"), itypes
                    )
                    if p:
                        params[node.pkey] = p
                continue
            itype = self._layer_itype(node)
            p, s = node.layer.init(self._stream.key(f"init/{node.pkey}"), itype)
            if p:
                params[node.pkey] = p
            if s:
                state[node.pkey] = s
        self.params = params
        self.net_state = state
        self.opt_state = self._tx.init(params)
        return self

    # -- pure forward ------------------------------------------------------
    def _forward(self, params, net_state, inputs: dict, *, training: bool, rng):
        """inputs: {input_name: array}. Returns ({output_name: logits}, new_state)."""
        acts: dict[str, jax.Array] = {}
        for name, x in inputs.items():
            x = entry_cast(x, self._bf16)
            acts[name] = x
        new_state = {}
        for i, node in enumerate(self._topo):
            xs = [acts[n] for n in node.inputs]
            if node.layer is not None:
                x = xs[0]
                if self._flatten[node.name]:
                    x = x.reshape(x.shape[0], -1)
                lp = params.get(node.pkey, {})
                ls = net_state.get(node.pkey, {})
                lrng = jax.random.fold_in(rng, i) if rng is not None else None
                y, ns = node.layer.apply(lp, ls, x, training=training, rng=lrng)
                if ns:
                    # shared-state layers (e.g. shared BatchNorm): the
                    # LAST call's statistics win for the step, matching
                    # call order
                    new_state[node.pkey] = ns
            elif node.vertex.HAS_PARAMS:
                lrng = jax.random.fold_in(rng, i) if rng is not None else None
                y = node.vertex.apply(
                    xs, params=params.get(node.pkey, {}), training=training, rng=lrng
                )
            else:
                y = node.vertex.apply(xs)
            acts[node.name] = y
        return {o: acts[o] for o in self.conf.network_outputs}, new_state

    def _reg_loss(self, params):
        # dedup by param_key: a shared layer's weights are penalized once
        seen = set()
        named = []
        for n in self.conf.nodes:
            if n.pkey in seen:
                continue
            seen.add(n.pkey)
            if n.layer is not None:
                named.append((n.pkey, n.layer))
            elif n.vertex.HAS_PARAMS:
                named.append((n.pkey, n.vertex))
        return regularization_loss(
            params,
            named,
        )

    # -- compiled train step ----------------------------------------------
    def _get_step_fn(self, n_masks: int, decode=None):
        """The per-batch graph step program.  With `decode` set (the
        single-input/single-output fused fit), the program takes raw
        features/labels and runs the lowered transform chain as its
        first stage — the loss body below is shared, so fused and host
        training cannot diverge."""
        key = (("train", n_masks) if decode is None
               else ("train_fused", decode.fingerprint))
        key = key + self._step_key_suffix()
        if key not in self._step_fns:

            def core(params, opt_state, net_state, step_i, features,
                     labels, masks):
                rng = SeedStream.fold(self._stream.root, step_i)
                inputs = dict(zip(self.conf.network_inputs, features))

                def loss_fn(p):
                    outs, new_state = self._forward(
                        p, net_state, inputs, training=True, rng=rng
                    )
                    total = jnp.zeros((), jnp.float32)
                    for (loss, act, fused, custom), oname, lab, m in zip(
                        self._out_specs,
                        self.conf.network_outputs,
                        labels,
                        masks,
                    ):
                        out = outs[oname]
                        if custom is not None:
                            if isinstance(custom, tuple):
                                _, node, fn = custom
                                total = total + fn(p.get(node, {}), out, lab, m)
                            else:
                                total = total + custom(out, lab, m)
                            continue
                        if not fused:
                            out = act(out.astype(jnp.float32))
                        total = total + compute_loss(loss, out, lab, m, from_logits=fused)
                    aux, new_state = pop_aux_losses(new_state)
                    return total + self._reg_loss(p) + aux, new_state

                (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    params
                )
                params, opt_state = self._apply_grads(params, opt_state, grads)
                merged_state = {**net_state, **new_state}
                return params, opt_state, merged_state, loss

            if decode is None:

                @partial(jax.jit, donate_argnums=(0, 1, 2))
                def step(params, opt_state, net_state, step_i, features,
                         labels, lmasks):
                    # len() of the label TUPLE is static structure,
                    # not a tracer read
                    masks = lmasks if n_masks else [None] * len(labels)  # tpulint: disable=RH101
                    return core(params, opt_state, net_state, step_i,
                                features, labels, masks)

            else:
                dec = decode.fn

                @partial(jax.jit, donate_argnums=(0, 1, 2))
                def step(params, opt_state, net_state, step_i, dec_step,
                         raw_feats, raw_labels):
                    # fused decode stage: raw single-input bytes in;
                    # the decode's label mask feeds the one output loss
                    # (graph steps have no features-mask path).
                    # dec_step is the feed's augmentation index (the
                    # batch's _decode_step) — the host fallback folds
                    # keys from the same feed counter
                    feats, labs, _fmask, lmask = dec(
                        dec_step, raw_feats, raw_labels
                    )
                    return core(params, opt_state, net_state, step_i,
                                (feats,), (labs,), (lmask,))

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _fit_batch_fused(self, batch: DataSet, decode) -> None:
        """Dispatch one fused decode+train graph program over a raw
        single-input batch (see SequentialModel._run_step_fused)."""
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime import faults
        from deeplearning4j_tpu.runtime.crash import oom_report_scope
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        step = self._get_step_fn(0, decode)
        with self._observe_step() as obs:
            with oom_report_scope(), active_mesh_scope(
                getattr(self, "_mesh", None)
            ):
                with obs.phase("host_stage"):
                    # fused-decode host boundary fault site
                    faults.maybe_fail("data.device_decode")
                    feats = place_batch(self, batch.features)
                    labs = place_batch(self, batch.labels, is_label=True)
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state,
                     loss) = step(
                        self.params, self.opt_state, self.net_state,
                        jnp.uint32(self.iteration),
                        jnp.uint32(getattr(batch, "_decode_step",
                                           self.iteration)),
                        feats, labs,
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = batch.num_examples
            self.iteration += 1
            self._count_device_decode(decode, feats, labs)
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)

    # -- data plumbing -----------------------------------------------------
    @staticmethod
    def _as_mds(batch) -> MultiDataSet:
        if isinstance(batch, MultiDataSet):
            return batch
        if isinstance(batch, DataSet):
            return MultiDataSet.from_dataset(batch)
        raise TypeError(f"cannot interpret {type(batch)} as a graph batch")

    @staticmethod
    def _as_batches(data, batch_size: int | None = None):
        """Normalize fit/evaluate input to an iterable of batches, accepting
        the same forms as SequentialModel ((x, y) tuple, DataSet,
        MultiDataSet, or any iterator of those)."""
        if isinstance(data, (DataSet, MultiDataSet)):
            return [data]
        if (
            isinstance(data, tuple)
            and len(data) == 2
            and all(isinstance(a, np.ndarray) for a in data)
        ):
            from deeplearning4j_tpu.data.iterator import NumpyDataSetIterator

            return NumpyDataSetIterator(data[0], data[1], batch_size or 32)
        if hasattr(data, "__iter__"):
            return data
        raise TypeError(f"cannot interpret {type(data)} as graph training data")

    def fit(self, data, epochs: int = 1, batch_size: int | None = None,
            steps_per_execution: int = 1) -> None:
        """steps_per_execution: see SequentialModel.fit — k optimizer
        steps per compiled program (masked batches, mismatched shapes and
        distributed models fall back to per-batch stepping; the listener
        caveat there applies)."""
        if self.params is None:
            self.init()
        iterator = self._as_batches(data, batch_size)
        self._donation_checked = False     # re-arm the one-time alias check
        self._ensure_watchdog()            # step-deadline hang detection
        use_multi = (
            steps_per_execution > 1
            and getattr(self, "_batch_sharding", None) is None
        )
        # device-compiled data pipeline (datavec/device.py): fused
        # decode is wired for the single-input/single-output per-batch
        # graph program; other graph shapes keep host transforms
        reason = None
        if use_multi:
            reason = "graph grouped (steps_per_execution) fit path"
        elif (len(self.conf.network_inputs) != 1
                or len(self.conf.network_outputs) != 1):
            reason = "multi-input/output graph"
        feed_src, decode = self._device_decode_feed(iterator, reason)
        self._device_decode = decode
        # software pipelining, same contract as SequentialModel.fit:
        # pull + device staging for batch N+1 overlap step N's compute
        feed = self._prefetch_feed(feed_src)
        try:
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch)
                if use_multi:
                    self._fit_epoch_multi(feed, steps_per_execution)
                else:
                    for batch in self._timed_batches(feed):
                        self._fit_one(batch)
                for lst in self.listeners:
                    lst.on_epoch_end(self, self.epoch)
                self.epoch += 1
                if hasattr(iterator, "reset"):
                    iterator.reset()
        finally:
            self._device_decode = None
            if feed is not feed_src:
                feed.close()
        for lst in self.listeners:
            # getattr: on_fit_end is newer than the SPI — tolerate
            # duck-typed listeners written against the original three hooks
            getattr(lst, "on_fit_end", lambda m: None)(self)

    def _fit_epoch_multi(self, iterator, spe: int) -> None:
        def group_ok(buf):
            return all(
                m.labels_masks is None
                and m.features_masks is None
                and tuple(a.shape for a in m.features)
                == tuple(a.shape for a in buf[0].features)
                and tuple(a.shape for a in m.labels)
                == tuple(a.shape for a in buf[0].labels)
                for m in buf
            )

        self._multi_iter_dev = None
        buf = []
        for batch in self._timed_batches(iterator):
            buf.append(self._as_mds(batch))
            if len(buf) == spe:
                if group_ok(buf):
                    self._fit_group(buf, self._run_steps_grouped)
                else:
                    for m in buf:
                        self._fit_one(m)
                    self._multi_iter_dev = None
                buf = []
        for m in buf:
            self._fit_one(m)
            self._multi_iter_dev = None

    def _get_step_fn_multi(self):
        key = ("train_multi",) + self._step_key_suffix()
        if key not in self._step_fns:

            @partial(jax.jit, donate_argnums=(0, 1, 2))
            def step(params, opt_state, net_state, step_i, features_k, labels_k):
                def one(carry, inp):
                    params, opt_state, net_state, si = carry
                    feats, labs = inp
                    rng = SeedStream.fold(self._stream.root, si)
                    inputs = dict(zip(self.conf.network_inputs, feats))

                    def loss_fn(p):
                        outs, new_state = self._forward(
                            p, net_state, inputs, training=True, rng=rng
                        )
                        total = jnp.zeros((), jnp.float32)
                        for (loss, act, fused, custom), oname, lab in zip(
                            self._out_specs, self.conf.network_outputs, labs
                        ):
                            out = outs[oname]
                            if custom is not None:
                                if isinstance(custom, tuple):
                                    _, node, fn = custom
                                    total = total + fn(p.get(node, {}), out, lab, None)
                                else:
                                    total = total + custom(out, lab, None)
                                continue
                            if not fused:
                                out = act(out.astype(jnp.float32))
                            total = total + compute_loss(
                                loss, out, lab, None, from_logits=fused
                            )
                        aux, new_state = pop_aux_losses(new_state)
                        return total + self._reg_loss(p) + aux, new_state

                    (loss, new_state), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params)
                    params, opt_state = self._apply_grads(params, opt_state, grads)
                    merged = {**net_state, **new_state}
                    return (params, opt_state, merged, si + 1), loss

                (params, opt_state, net_state, si), losses = jax.lax.scan(
                    one,
                    (params, opt_state, net_state, step_i),
                    (features_k, labels_k),
                )
                return params, opt_state, net_state, losses, si

            self._step_fns[key] = self._register_program(key, step)
        return self._step_fns[key]

    def _run_steps_grouped(self, group) -> None:
        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        # accepts DataSet or MultiDataSet (direct callers like the bench);
        # _as_mds is an identity on already-converted batches
        group = [self._as_mds(m) for m in group]
        for m in group:
            self._check_mds(m)
        step = self._get_step_fn_multi()
        k = len(group)
        n_in = len(self.conf.network_inputs)
        n_out = len(self.conf.network_outputs)
        with self._observe_step(k) as obs:
            with oom_report_scope():
                with obs.phase("host_stage"):
                    feats = tuple(
                        jnp.stack([jnp.asarray(m.features[i]) for m in group])
                        for i in range(n_in)
                    )
                    labs = tuple(
                        jnp.stack([jnp.asarray(m.labels[i]) for m in group])
                        for i in range(n_out)
                    )
                    if getattr(self, "_multi_iter_dev", None) is None:
                        self._multi_iter_dev = jax.device_put(
                            np.uint32(self.iteration)
                        )
                with obs.phase("dispatch"):
                    (self.params, self.opt_state, self.net_state, losses,
                     self._multi_iter_dev) = step(
                        self.params, self.opt_state, self.net_state,
                        self._multi_iter_dev, feats, labs,
                    )
                with obs.phase("device_sync"):
                    obs.sync(losses)
            self.last_batch_size = group[-1].num_examples
            self._finish_grouped_steps(losses, k)

    def _check_mds(self, mds) -> None:
        if len(mds.features) != len(self.conf.network_inputs):
            raise ValueError(
                f"graph has {len(self.conf.network_inputs)} inputs, batch has "
                f"{len(mds.features)} feature arrays"
            )
        if len(mds.labels) != len(self.conf.network_outputs):
            raise ValueError(
                f"graph has {len(self.conf.network_outputs)} outputs, batch has "
                f"{len(mds.labels)} label arrays"
            )

    def fit_batch(self, batch) -> None:
        if self.params is None:
            self.init()
        if (self._device_decode is not None
                and getattr(batch, "_raw_for_device_decode", False)):
            # raw-tagged batch BEFORE the MultiDataSet conversion (the
            # conversion would drop the routing tag)
            if not isinstance(batch, DataSet):
                # the transform-chain protocol is single-input and
                # DataSet-shaped; a tagged batch of any other type has
                # no decode route and must never reach the step
                # undecoded
                raise TypeError(
                    "raw device-decode batch must be a DataSet, got "
                    f"{type(batch).__name__}"
                )
            if batch.features_mask is None and batch.labels_mask is None:
                self._fit_batch_fused(batch, self._device_decode)
                return
            # masked raw batch: host-decode (masks thread through the
            # chain) and take the normal masked step below.  (_RawFeed
            # host-decodes masked batches itself; this is the defensive
            # net for hand-tagged batches.)
            batch = self._device_decode.host(
                getattr(batch, "_decode_step", self.iteration), batch
            )
        mds = self._as_mds(batch)
        self._check_mds(mds)
        masks = mds.labels_masks
        if masks is not None and len(masks) != len(mds.labels):
            raise ValueError(
                f"labels_masks has {len(masks)} entries for {len(mds.labels)} "
                "outputs (one mask per output, use None entries for unmasked)"
            )
        n_masks = len(masks) if masks is not None else 0
        step = self._get_step_fn(n_masks)
        from deeplearning4j_tpu.parallel.data_parallel import place_batch
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        from deeplearning4j_tpu.runtime.crash import oom_report_scope

        with self._observe_step() as obs:
            # staging stays INSIDE the oom/mesh scopes (a device OOM while
            # placing the batch must still write the crash report)
            with oom_report_scope(), active_mesh_scope(
                getattr(self, "_mesh", None)
            ):
                with obs.phase("host_stage"):
                    feats = tuple(place_batch(self, f) for f in mds.features)
                    labs = tuple(
                        place_batch(self, l, is_label=True)
                        for l in mds.labels
                    )
                    lms = (
                        tuple(
                            place_batch(self, m, is_mask=True) for m in masks
                        )
                        if masks is not None else ()
                    )
                with obs.phase("dispatch"):
                    self.params, self.opt_state, self.net_state, loss = step(
                        self.params,
                        self.opt_state,
                        self.net_state,
                        jnp.uint32(self.iteration),
                        feats, labs, lms,
                    )
                with obs.phase("device_sync"):
                    obs.sync(loss)
            self._last_score = loss
            self.last_batch_size = mds.num_examples
            self.iteration += 1
            with obs.phase("listeners"):
                self._dispatch_iteration(loss)

    # -- layerwise unsupervised pretraining --------------------------------
    def pretrain(self, data, epochs: int = 1) -> None:
        """Greedy layerwise pretraining in topological order (reference
        ComputationGraph.pretrain(DataSetIterator))."""
        for node in self._topo:
            if node.layer is not None and getattr(node.layer, "PRETRAINABLE", False):
                self.pretrain_layer(node.name, data, epochs=epochs)

    def pretrain_layer(self, name: str, data, epochs: int = 1) -> float:
        """Unsupervised pretraining of one named layer node (reference
        ComputationGraph.pretrainLayer(layerName, iter)): ancestors run
        in inference mode, (prefix -> pretrain_loss -> grad -> updater)
        for this node's params is one donated-buffer XLA step."""
        if self.params is None:
            self.init()
        by_name = {n.name: n for n in self.conf.nodes}
        if name not in by_name:
            raise KeyError(f"no layer node named {name!r}")
        node = by_name[name]
        layer = node.layer
        if layer is None or not getattr(layer, "PRETRAINABLE", False):
            raise ValueError(
                f"node {name!r} is not pretrainable; only AutoEncoder/"
                "VariationalAutoencoder layers support unsupervised "
                "pretraining"
            )
        tx = with_gradient_clipping(
            self.conf.updater.to_optax(self.conf.steps_per_epoch),
            self.conf.gradient_clip_value,
            self.conf.gradient_clip_norm,
        )
        opt_state = tx.init(self.params[name])
        frozen = {k: v for k, v in self.params.items() if k != name}

        def prefix(fparams, features):
            """Inference-mode topo walk up to `name`'s input activation."""
            acts = {}
            for iname, x in zip(self.conf.network_inputs, features):
                x = entry_cast(x, self._bf16)
                acts[iname] = x
            for nd in self._topo:
                if nd.name == name:
                    break
                xs = [acts[n] for n in nd.inputs]
                if nd.layer is not None:
                    x = xs[0]
                    if self._flatten[nd.name]:
                        x = x.reshape(x.shape[0], -1)
                    y, _ = nd.layer.apply(
                        fparams.get(nd.pkey, {}),
                        self.net_state.get(nd.pkey, {}),
                        x, training=False, rng=None,
                    )
                elif nd.vertex.HAS_PARAMS:
                    y = nd.vertex.apply(
                        xs, params=fparams.get(nd.pkey, {}),
                        training=False, rng=None,
                    )
                else:
                    y = nd.vertex.apply(xs)
                acts[nd.name] = y
            x = acts[node.inputs[0]]
            if self._flatten[name]:
                x = x.reshape(x.shape[0], -1)
            return x.astype(jnp.float32)

        from functools import partial as _partial

        @_partial(jax.jit, donate_argnums=(0, 1))
        def pstep(lp, opt_state, fparams, step_i, features):
            rng = SeedStream.fold(self._stream.root, step_i)

            def loss_fn(lp):
                x = prefix(fparams, features)
                return layer.pretrain_loss(lp, jax.lax.stop_gradient(x), rng)

            loss, grads = jax.value_and_grad(loss_fn)(lp)
            updates, opt_state = tx.update(grads, opt_state, lp)
            lp = jax.tree.map(lambda p, u: p + u.astype(p.dtype), lp, updates)
            return lp, opt_state, loss

        lp = self.params.pop(name)
        loss = float("nan")
        step_i = 0
        iterator = self._as_batches(data, None)
        if epochs > 1 and not hasattr(iterator, "reset"):
            # a plain generator would be exhausted after epoch 1 and the
            # remaining epochs would silently run zero steps
            iterator = list(iterator)
        try:
            for _ in range(epochs):
                for batch in iterator:
                    mds = self._as_mds(batch)
                    feats = tuple(jnp.asarray(f) for f in mds.features)
                    lp, opt_state, loss = pstep(
                        lp, opt_state, frozen, jnp.uint32(step_i), feats
                    )
                    step_i += 1
                if hasattr(iterator, "reset"):
                    iterator.reset()
        finally:
            self.params[name] = lp
        return float(loss)

    # -- inference ---------------------------------------------------------
    def _get_infer_fn(self):
        if self._infer_fn is None:

            @jax.jit
            def infer(params, net_state, features):
                inputs = dict(zip(self.conf.network_inputs, features))
                outs, _ = self._forward(params, net_state, inputs, training=False, rng=None)
                result = []
                for (loss, act, fused, custom), oname in zip(
                    self._out_specs, self.conf.network_outputs
                ):
                    result.append(act(outs[oname].astype(jnp.float32)))
                return tuple(result)

            from deeplearning4j_tpu.observe import cost

            self._infer_fn = cost.register_attr_program(
                self, "_infer_fn", "infer",
                ("infer",) + self._step_key_suffix(), infer,
            )
        return self._infer_fn

    def output(self, *features) -> tuple[jax.Array, ...]:
        """Activated outputs for the given inputs (one array per network
        output; pass one array per network input)."""
        if self.params is None:
            self.init()
        if len(features) != len(self.conf.network_inputs):
            raise ValueError(
                f"graph has {len(self.conf.network_inputs)} inputs "
                f"{self.conf.network_inputs}, got {len(features)} arrays"
            )
        from deeplearning4j_tpu.runtime.mesh import active_mesh_scope

        with active_mesh_scope(getattr(self, "_mesh", None)):
            outs = self._get_infer_fn()(self.params, self.net_state, tuple(features))
        return outs if len(outs) > 1 else outs[0]

    def predict(self, *features) -> np.ndarray:
        out = self.output(*features)
        first = out[0] if isinstance(out, tuple) else out
        return np.asarray(jnp.argmax(first, axis=-1))

    def evaluate(self, data, output_index: int = 0):
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation

        iterator = self._as_batches(data)
        ev = Evaluation()
        by_name = {n.name: n for n in self.conf.nodes}
        out_layer = by_name[self.conf.network_outputs[output_index]].layer
        for batch in iterator:
            mds = self._as_mds(batch)
            out = self.output(*mds.features)
            arr = out[output_index] if isinstance(out, tuple) else out
            if out_layer is not None and hasattr(out_layer, "evaluation_output"):
                # custom heads: extract class probabilities from the raw
                # apply() output (see SequentialModel.evaluate)
                arr = out_layer.evaluation_output(
                    self.params.get(out_layer.name, {}), arr
                )
            mask = None
            if mds.labels_masks is not None:
                mask = mds.labels_masks[output_index]
            ev.eval(mds.labels[output_index], np.asarray(arr), mask=mask)
        return ev

    def score(self, batch) -> float:
        mds = self._as_mds(batch)
        inputs = dict(zip(self.conf.network_inputs, [jnp.asarray(f) for f in mds.features]))
        outs, _ = self._forward(self.params, self.net_state, inputs, training=False, rng=None)
        masks = mds.labels_masks or (None,) * len(mds.labels)
        total = jnp.zeros((), jnp.float32)
        for (loss, act, fused, custom), oname, lab, m in zip(
            self._out_specs, self.conf.network_outputs, mds.labels, masks
        ):
            out = outs[oname]
            if custom is not None:
                if isinstance(custom, tuple):
                    _, node, fn = custom
                    total = total + fn(
                        self.params.get(node, {}), out, jnp.asarray(lab), m
                    )
                else:
                    total = total + custom(out, jnp.asarray(lab), m)
                continue
            if not fused:
                out = act(out.astype(jnp.float32))
            total = total + compute_loss(loss, out, jnp.asarray(lab), m, from_logits=fused)

        return float(total + self._reg_loss(self.params))

    def clone(self) -> "GraphModel":
        m = GraphModel(self.conf)
        if self.params is not None:
            m.params = jax.tree.map(jnp.copy, self.params)
            m.net_state = jax.tree.map(jnp.copy, self.net_state)
            m.opt_state = jax.tree.map(jnp.copy, self.opt_state)
        return m
