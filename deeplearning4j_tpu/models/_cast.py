"""Network-entry dtype policy shared by the model classes.

The ETL tier ships uint8 image batches over the host->device link (4x
fewer bytes than float32 — on a tunneled dev chip the link is the
bottleneck, and on a TPU-VM it still quarters DMA traffic); the cast to
the compute dtype happens HERE, inside the jitted step, so the wire
carries bytes and the MXU sees bf16/f32.  Reference role: the
ImageRecordReader -> normalizer -> fit() pipeline (SURVEY.md §2.2
DataVec ETL), which moves float buffers; shipping uint8 is the
TPU-native improvement.
"""

from __future__ import annotations

import jax.numpy as jnp


def entry_cast(x, bf16: bool):
    """Cast a network input to the compute dtype.

    - float inputs follow the bf16 compute flag (unchanged behavior);
    - uint8 inputs are IMAGE bytes: cast to the compute dtype on device,
      value-preserving (0..255 stays 0..255 — normalizers have already
      been applied host-side in integer space or run as graph ops);
    - wider integer inputs (int32/int64 token ids for embedding layers)
      pass through untouched.
    """
    if jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(jnp.bfloat16) if bf16 else x
    if x.dtype == jnp.uint8:
        return x.astype(jnp.bfloat16 if bf16 else jnp.float32)
    return x
