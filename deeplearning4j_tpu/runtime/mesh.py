"""Device-mesh construction — the scale-out topology substrate.

The reference organizes multi-device work as replica threads
(ParallelWrapper) and a UDP tree mesh (MeshOrganizer in
nd4j-parameter-server — SURVEY.md §2.3, §5.8).  TPU-native, topology is a
`jax.sharding.Mesh` with named axes and scale-out is sharding over those
axes; XLA inserts the collectives.  Axis-name conventions used throughout
the framework:

    "data"   — data parallel (batch dim)
    "model"  — tensor/model parallel (feature/head dims)
    "pipe"   — pipeline-parallel stage axis
    "seq"    — sequence/context parallel (ring attention axis)
    "expert" — expert parallel (MoE)

A MeshSpec names the axes present and their sizes; `make_mesh` lays the
available devices out accordingly.  On CPU, `virtual_cpu_devices` documents
the XLA_FLAGS trick used by the test-suite (the TPU-build analog of the
reference's "Spark local[N] / Aeron loopback" multi-node-without-a-cluster
patterns, SURVEY.md §4.2).
"""

from __future__ import annotations

import dataclasses
import math
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named axis layout for a device mesh.

    Axis sizes of -1 mean "fill with all remaining devices" (at most one
    axis may be -1).  Axes of size 1 are kept: a size-1 axis lets the same
    pjit-ted step run unchanged at any scale.
    """

    axes: tuple[tuple[str, int], ...] = ((DATA_AXIS, -1),)

    @staticmethod
    def data_parallel() -> "MeshSpec":
        return MeshSpec(((DATA_AXIS, -1),))

    @staticmethod
    def of(**axis_sizes: int) -> "MeshSpec":
        return MeshSpec(tuple(axis_sizes.items()))

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    def resolve(self, n_devices: int) -> tuple[tuple[str, int], ...]:
        sizes = [s for _, s in self.axes]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {self.axes}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {self.axes} need {fixed} devices, have {n_devices}"
            )
        return tuple((name, size) for (name, _), size in zip(self.axes, sizes))


def make_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh from the spec over the given (default: all) devices."""
    spec = spec or MeshSpec.data_parallel()
    devs = list(devices) if devices is not None else jax.devices()
    resolved = spec.resolve(len(devs))
    shape = tuple(size for _, size in resolved)
    names = tuple(name for name, _ in resolved)
    arr = np.asarray(devs, dtype=object).reshape(shape)
    return Mesh(arr, axis_names=names)


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, axis_names=None,
              check_vma=None, check_rep=None):
    """`jax.shard_map` compatibility shim — the ONE entry point the
    framework (and its tests) use for per-shard SPMD bodies.

    jax >= 0.6 exposes ``jax.shard_map(f, mesh=..., in_specs=...,
    out_specs=..., axis_names=..., check_vma=...)``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map(f, mesh, in_specs,
    out_specs, check_rep=..., auto=...)`` — same GSPMD lowering, older
    spelling.  This shim maps between them:

    - ``check_vma`` (new name) / ``check_rep`` (old name) are the same
      replication-checking knob; whichever is given is forwarded under
      the API's own name.
    - ``axis_names`` restricts which mesh axes the body is manual over;
      the legacy API expresses the complement via ``auto``.
    """
    native = getattr(jax, "shard_map", None)
    rep = check_vma if check_vma is not None else check_rep
    if native is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if rep is not None:
            kwargs["check_vma"] = bool(rep)
        return native(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _legacy

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if rep is not None:
        kwargs["check_rep"] = bool(rep)
    if axis_names is not None:
        # Axes outside `axis_names` would be "auto" (GSPMD-partitioned
        # around the manual body).  Legacy shard_map's auto support is
        # broken under jit — the SPMD partitioner hits an UNIMPLEMENTED
        # PartitionId / a CHECK abort — so: size-1 leftovers fold into
        # the manual set (semantically free: nothing is sharded over
        # them), and a real >1 auto axis raises HERE, actionably,
        # instead of aborting the process inside XLA.
        auto = frozenset(
            a for a in mesh.axis_names
            if a not in axis_names and mesh.shape[a] > 1
        )
        if auto:
            raise NotImplementedError(
                f"this jax ({jax.__version__}) cannot run a shard_map "
                f"manual over {sorted(axis_names)} while axes "
                f"{sorted(auto)} (size > 1) stay GSPMD-auto; shrink the "
                "auto axes to size 1 or upgrade jax for partial-auto "
                "shard_map"
            )
    return _legacy(f, **kwargs)


def axis_size(name: str):
    """Size of a named mesh axis from INSIDE a traced per-shard body.

    ``jax.lax.axis_size`` only exists on newer jax; the 0.4.x spelling
    is the idiomatic ``lax.psum(1, name)``, which constant-folds to the
    static axis size.
    """
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    return jax.lax.psum(1, name)


def virtual_cpu_devices(n: int) -> str:
    """The env-var incantation for an n-device virtual CPU platform.

    Must be set BEFORE jax initializes its backends (the test conftest does
    this).  Returned as a string for documentation/subprocess use.
    """
    return f"--xla_force_host_platform_device_count={n}"


def single_device_mesh(axis: str = DATA_AXIS) -> Mesh:
    """1-device mesh so sharded code paths run unchanged on one chip."""
    return Mesh(np.asarray(jax.devices()[:1], dtype=object).reshape((1,)), (axis,))


# -- active-mesh context ----------------------------------------------------
# Layer `apply()` functions are traced deep inside a model's jitted step and
# have a fixed signature; layers whose lowering depends on the mesh (e.g.
# SelfAttentionLayer with seq_parallel="ring" wrapping its core in shard_map)
# read the mesh from this trace-time context, which the models set around
# their compiled-step invocations (distribute() stores the mesh on the model).

_ACTIVE_MESH: Mesh | None = None


class active_mesh_scope:
    """Context manager installing `mesh` as the active mesh for layer
    tracing.  Reentrant; None is a valid (no-mesh) value."""

    def __init__(self, mesh: Mesh | None):
        self._mesh = mesh
        self._prev: Mesh | None = None

    def __enter__(self):
        global _ACTIVE_MESH
        self._prev = _ACTIVE_MESH
        _ACTIVE_MESH = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _ACTIVE_MESH
        _ACTIVE_MESH = self._prev
        return False


def active_mesh() -> Mesh | None:
    return _ACTIVE_MESH
