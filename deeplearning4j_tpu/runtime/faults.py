"""Deterministic fault injection — make every failure path provokable.

The reference stack's fault story is tested with dummy/delayed transports
(SURVEY.md §4.2); large-scale systems go further and treat fault tolerance
as a *testable* subsystem.  This module is the seam: named sites on the
control plane, the checkpoint path and the input pipeline consult
``maybe_fail(site)``, and an armed `FaultPlan` decides — deterministically —
whether that call raises, delays, dies, or asks the site to corrupt its own
output.

Sites wired today:

  ``coordinator.rpc``    every CoordinatorClient request attempt
  ``heartbeat.send``     the worker heartbeat (before the rpc)
  ``checkpoint.write``   ModelSerializer.write_model entry (may return
                         ``"truncate"`` — the site chops the published bytes)
  ``checkpoint.fsync``   between the zip landing in the tmp file and its
                         atomic publish (a ``kill`` here IS kill-9-mid-write)
  ``data.next_batch``    the fit loops' batch pull
  ``data.prefetch``      the PrefetchIterator producer thread, before each
                         base-iterator pull + device staging
  ``data.decode``        the fit loops' per-batch decode boundary, after
                         the pull (``corrupt`` ⇒ the site NaN-poisons the
                         batch — the poison-batch path; ``raise`` ⇒ a
                         per-record decode failure the quarantine absorbs)
  ``device.sync``        the fit loops' device_sync barrier (``delay`` ⇒
                         a simulated wedged step under the watchdog)
  ``serving.admit``      InferenceServer.submit entry (``delay`` ⇒ slow
                         admission; other kinds ⇒ explicit admit_fault
                         rejection)
  ``serving.infer``      the serving batcher's per-batch dispatch
                         (``delay`` ⇒ wedged dispatch under the serving
                         watchdog, ``corrupt`` ⇒ NaN outputs)
  ``serving.hotswap``    the weight-push path (``truncate``/``corrupt``
                         ⇒ torn/poisoned push that must roll back)
  ``serving.route``      the fleet Router's submit entry (``raise`` ⇒
                         explicit route_fault rejection, ``delay`` ⇒
                         slow front door)
  ``serving.canary``     FleetDeployer's canary verification
                         (``corrupt`` ⇒ canary output mismatch ⇒ the
                         deploy rolls back)
  ``serving.prefill``    the generation engine's per-stream prefill
                         dispatch (``raise`` ⇒ the stream fails
                         explicitly, its pages released)
  ``serving.decode``     the generation engine, before each batched
                         decode step (``raise`` ⇒ a failed step that
                         fails every in-flight stream; ``delay`` ⇒ a
                         wedged step under the generation watchdog)
  ``serving.draft``      the speculative drafter, per drafting stream
                         (``raise`` ⇒ that stream falls back to plain
                         decode for good; ``corrupt`` ⇒ garbage drafts
                         that must all be rejected, output unchanged)
  ``kv.alloc``           PagedKVCache page allocation (``raise`` ⇒
                         injected pool exhaustion ⇒ an explicit
                         kv_exhausted 429)

Plan grammar (also the ``DL4J_TPU_FAULT_PLAN`` env value, so subprocess
workers inherit the plan from their spawner's environment)::

    plan    := clause (";" clause)*
    clause  := SITE ":" KIND [":" param ("," param)*]
    KIND    := raise | delay | truncate | corrupt | kill
    param   := nth=N     fire exactly once, on the Nth consult (1-based)
             | every=N   fire on every Nth consult
             | p=F       fire with probability F per consult (seeded)
             | seed=N    RNG seed for p-triggers (default 0: deterministic)
             | max=N     stop firing after N fires
             | secs=F    sleep length for delay (default 0.05)
             | exc=NAME  exception for raise: connection (default) | timeout
                         | runtime

    DL4J_TPU_FAULT_PLAN="coordinator.rpc:raise:every=3;checkpoint.write:truncate:nth=2"

Zero overhead disarmed: ``maybe_fail`` is one module-global load and a
``None`` check per site — the same pattern as the trace spans.  Armed, each
consult takes a small lock, bumps the site counter, and evaluates the
site's rules; fires land on the telemetry spine as
``dl4jtpu_faults_injected_total{site=...}``.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

_ENV_VAR = "DL4J_TPU_FAULT_PLAN"

_KINDS = ("raise", "delay", "truncate", "corrupt", "kill")

# The site registry: every `maybe_fail("<site>")` call in the package
# must use a name listed here (machine-checked by tpulint rule RG302 —
# an unregistered site is a fault plan that silently never fires).
# Plans may still name ad-hoc sites (tests do); this table is the
# contract for PRODUCTION call sites, not a runtime gate.
SITES: dict = {
    "coordinator.rpc": "every CoordinatorClient request attempt",
    "heartbeat.send": "the worker heartbeat, before the rpc",
    "checkpoint.write": "ModelSerializer.write_model entry (may return "
                        "'truncate' — the site chops published bytes)",
    "checkpoint.fsync": "between the zip landing in the tmp file and "
                        "its atomic publish (kill here = kill-9 "
                        "mid-write)",
    "data.next_batch": "the fit loops' batch pull",
    "data.prefetch": "the PrefetchIterator producer thread, before each "
                     "base-iterator pull + device staging",
    "data.decode": "the fit loops' per-batch decode boundary, after the "
                   "pull ('corrupt' NaN-poisons the batch; 'raise' is a "
                   "per-record decode failure)",
    "device.sync": "the fit loops' device_sync barrier ('delay' "
                   "simulates a wedged step under the watchdog)",
    "data.device_decode": "the fused-decode fit paths' host boundary, "
                          "before staging raw bytes and dispatching the "
                          "decode+step program",
    "serving.admit": "InferenceServer.submit entry ('delay' = a slow "
                     "admission path; 'raise'/other kinds reject the "
                     "request explicitly as admit_fault)",
    "serving.infer": "the serving batcher, before each batched infer "
                     "dispatch ('delay' = a wedged dispatch under the "
                     "serving watchdog; 'raise' = a failed dispatch; "
                     "'corrupt' NaN-poisons the outputs — the "
                     "finiteness screen + breaker path)",
    "serving.hotswap": "InferenceServer.push_weights entry ('truncate' "
                       "= a torn push that dropped leaves; 'corrupt' "
                       "NaN-poisons the staged params; both must roll "
                       "back to the serving weights)",
    "serving.route": "the fleet Router's submit entry ('raise' = a "
                     "misrouted request the front door rejects "
                     "explicitly as route_fault; 'delay' = a slow "
                     "front door)",
    "serving.canary": "FleetDeployer's per-replica canary verification "
                      "('corrupt' perturbs the observed canary outputs "
                      "— the golden mismatch must roll the whole "
                      "deploy back)",
    "serving.prefill": "the generation engine's per-stream prefill "
                       "dispatch ('raise' = the stream fails "
                       "explicitly and its KV pages are released)",
    "serving.decode": "the generation engine, before each batched "
                      "decode step ('raise' = a failed step that "
                      "fails every in-flight stream and releases "
                      "their pages; 'delay' = a wedged step under "
                      "the generation watchdog)",
    "serving.draft": "the speculative drafter, once per drafting "
                     "stream per step ('raise' = the stream's drafter "
                     "latches OFF and it falls back to plain decode, "
                     "overhang pages truncated; 'corrupt' = garbage "
                     "drafts the verify pass must fully reject with "
                     "output unchanged)",
    "kv.alloc": "PagedKVCache page allocation ('raise' = injected "
                "pool exhaustion — the request is rejected with an "
                "explicit kv_exhausted 429, never a silent stall)",
}


class InjectedFault(ConnectionError):
    """Raised at a fault site by an armed plan (transient-shaped: subclasses
    ConnectionError/OSError so retry policies treat it like the real thing)."""


class InjectedTimeout(TimeoutError):
    """`exc=timeout` variant (TimeoutError is an OSError — still retryable)."""


class InjectedError(RuntimeError):
    """`exc=runtime` variant — NOT retryable; exercises give-up paths."""


_EXC_BY_NAME = {
    "connection": InjectedFault,
    "timeout": InjectedTimeout,
    "runtime": InjectedError,
}


class FaultRule:
    """One clause of a plan: a trigger + an action bound to a site."""

    def __init__(self, site: str, kind: str, *, nth: Optional[int] = None,
                 every: Optional[int] = None, p: Optional[float] = None,
                 seed: int = 0, max_fires: Optional[int] = None,
                 secs: float = 0.05, exc: str = "connection"):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {_KINDS})")
        if exc not in _EXC_BY_NAME:
            raise ValueError(
                f"unknown exc {exc!r} (one of {sorted(_EXC_BY_NAME)})"
            )
        triggers = sum(x is not None for x in (nth, every, p))
        if triggers > 1:
            raise ValueError("pick ONE trigger per clause: nth=, every= or p=")
        if triggers == 0:
            nth = 1                       # default: one-shot on first consult
        self.site = site
        self.kind = kind
        self.nth = nth
        self.every = every
        self.p = p
        self.seed = int(seed)
        self.max_fires = max_fires
        self.secs = float(secs)
        self.exc = exc
        # runtime state (reset by FaultPlan.arm)
        self.fires = 0
        self._rng = None

    def reset(self) -> None:
        self.fires = 0
        if self.p is not None:
            import random

            self._rng = random.Random(self.seed)

    def should_fire(self, consult_no: int) -> bool:
        """consult_no is 1-based, per-site.  Caller holds the plan lock."""
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.nth is not None:
            return consult_no == self.nth
        if self.every is not None:
            return consult_no % self.every == 0
        return self._rng.random() < self.p

    def spec(self) -> str:
        params = []
        if self.nth is not None and self.nth != 1:
            params.append(f"nth={self.nth}")
        elif self.nth == 1:
            params.append("nth=1")
        if self.every is not None:
            params.append(f"every={self.every}")
        if self.p is not None:
            params.append(f"p={self.p}")
            params.append(f"seed={self.seed}")
        if self.max_fires is not None:
            params.append(f"max={self.max_fires}")
        if self.kind == "delay":
            params.append(f"secs={self.secs}")
        if self.exc != "connection":
            params.append(f"exc={self.exc}")
        head = f"{self.site}:{self.kind}"
        return head + (":" + ",".join(params) if params else "")


class FaultPlan:
    """A seedable registry of rules keyed by site, with per-site consult
    counters.  Thread-safe: heartbeat threads and the training loop consult
    concurrently."""

    def __init__(self, rules: list[FaultRule]):
        self._rules: dict[str, list[FaultRule]] = {}
        for r in rules:
            self._rules.setdefault(r.site, []).append(r)
        self._lock = threading.Lock()
        self._consults: dict[str, int] = {}
        for r in rules:
            r.reset()

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        rules = []
        for clause in text.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            parts = clause.split(":")
            if len(parts) < 2:
                raise ValueError(
                    f"bad fault clause {clause!r}: want site:kind[:params]"
                )
            site, kind = parts[0].strip(), parts[1].strip()
            kw: dict = {}
            if len(parts) > 2:
                for param in ":".join(parts[2:]).split(","):
                    param = param.strip()
                    if not param:
                        continue
                    if param == "once":
                        kw["nth"] = 1
                        continue
                    k, _, v = param.partition("=")
                    k = k.strip()
                    v = v.strip()
                    if k in ("nth", "every", "seed"):
                        kw[k] = int(v)
                    elif k == "max":
                        kw["max_fires"] = int(v)
                    elif k in ("p", "secs"):
                        kw[k] = float(v)
                    elif k == "exc":
                        kw["exc"] = v
                    else:
                        raise ValueError(
                            f"unknown fault param {k!r} in clause {clause!r}"
                        )
            rules.append(FaultRule(site, kind, **kw))
        if not rules:
            raise ValueError(f"empty fault plan {text!r}")
        return cls(rules)

    def spec(self) -> str:
        """Serialize back to the grammar — hand this to a subprocess's
        ``DL4J_TPU_FAULT_PLAN`` so the fleet inherits the plan."""
        return ";".join(
            r.spec() for rs in self._rules.values() for r in rs
        )

    def sites(self) -> list[str]:
        return sorted(self._rules)

    def stats(self) -> dict:
        """{site: {"consults": n, "fires": n}} — assert on these in tests."""
        with self._lock:
            return {
                site: {
                    "consults": self._consults.get(site, 0),
                    "fires": sum(r.fires for r in rs),
                }
                for site, rs in self._rules.items()
            }

    def consult(self, site: str) -> Optional[str]:
        rules = self._rules.get(site)
        if not rules:
            return None
        fired: Optional[FaultRule] = None
        with self._lock:
            n = self._consults.get(site, 0) + 1
            self._consults[site] = n
            for r in rules:
                if r.should_fire(n):
                    r.fires += 1
                    fired = r
                    break
        if fired is None:
            return None
        _count_fire(site)
        if fired.kind == "delay":
            time.sleep(fired.secs)
            return None
        if fired.kind == "kill":
            # the real thing: no atexit, no finally blocks, no flush —
            # exactly what a preemption or OOM-killer does to a worker
            os.kill(os.getpid(), signal.SIGKILL)
            return None                           # pragma: no cover
        if fired.kind == "raise":
            raise _EXC_BY_NAME[fired.exc](
                f"injected fault at {site} (consult #{n})"
            )
        return fired.kind                 # cooperative: "truncate"/"corrupt"


def _count_fire(site: str) -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_faults_injected_total").inc(site=site)
    except Exception:  # tpulint: disable=EH402
        pass             # telemetry must never mask the injected fault —
        # and this path runs INSIDE the injected failure, where even a
        # logging call can recurse into a faulted subsystem


# -- process-global arming --------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def arm(plan) -> FaultPlan:
    """Arm a plan process-wide (str in the grammar, or a FaultPlan).
    Counters reset on every arm()."""
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    for rs in plan._rules.values():
        for r in rs:
            r.reset()
    with plan._lock:
        plan._consults.clear()
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def is_armed() -> bool:
    return _PLAN is not None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def maybe_fail(site: str) -> Optional[str]:
    """The per-site hook.  Disarmed (the default): one global load + None
    check — nothing else.  Armed: consult the plan; may raise, sleep, kill
    the process, or return an action string ("truncate") the site applies
    to its own output."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.consult(site)


# Subprocess inheritance: workers spawned with DL4J_TPU_FAULT_PLAN in their
# environment arm themselves at import time, before any site is consulted.
_env_plan = os.environ.get(_ENV_VAR, "").strip()
if _env_plan:
    arm(_env_plan)
del _env_plan
