"""Multi-host runtime bootstrap — the cluster story's data plane.

Reference role: DL4J scales past one box with Spark driver/executor
orchestration plus an Aeron UDP mesh for gradient traffic
(`SparkDl4jMultiLayer`, `SharedTrainingMaster`, `ModelParameterServer` —
SURVEY.md §2.2, §3.5).  TPU-native, the data plane is jax.distributed: every
host process runs the SAME SPMD program, `jax.devices()` spans all hosts,
and GSPMD inserts cross-host collectives that ride ICI within a slice and
DCN across slices.  There is no parameter server and no gossip — sync
full-precision AllReduce replaces the threshold-encoded async exchange by
design (SURVEY.md §5.8).

The control plane (membership, heartbeat, elastic restart orchestration —
the Spark-driver/MeshOrganizer role) lives in
`deeplearning4j_tpu.runtime.coordinator`; this module owns only the JAX
runtime bring-up.

Multi-node-without-a-cluster (SURVEY.md §4.2): N local processes, CPU
platform, gloo collectives — the Spark-`local[N]`/Aeron-loopback analog.
`DistributedConfig(local_device_count=k, platform="cpu")` makes one host
process simulate a k-device worker; the test-suite drives whole worker
fleets this way.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

log = logging.getLogger("deeplearning4j_tpu")

ENV_COORDINATOR = "DL4JTPU_COORDINATOR"       # host:port of process 0
ENV_NUM_PROCESSES = "DL4JTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "DL4JTPU_PROCESS_ID"
ENV_LOCAL_DEVICES = "DL4JTPU_LOCAL_DEVICES"   # CPU simulation only
ENV_PLATFORM = "DL4JTPU_PLATFORM"             # "cpu" to force the simulator


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """How this process joins the cluster.

    All-None (on Cloud TPU) lets jax.distributed auto-detect the slice
    topology from the TPU metadata server.  For explicit clusters (and for
    the CPU simulator) give coordinator_address + num_processes +
    process_id.
    """

    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # CPU-simulation knobs (multi-node-without-a-cluster):
    local_device_count: Optional[int] = None
    platform: Optional[str] = None
    # data-plane failure-detection latency (None = jax default, 100s)
    heartbeat_timeout_seconds: Optional[int] = None

    @staticmethod
    def from_env() -> "DistributedConfig":
        def _int(name):
            v = os.environ.get(name)
            return int(v) if v not in (None, "") else None

        return DistributedConfig(
            coordinator_address=os.environ.get(ENV_COORDINATOR) or None,
            num_processes=_int(ENV_NUM_PROCESSES),
            process_id=_int(ENV_PROCESS_ID),
            local_device_count=_int(ENV_LOCAL_DEVICES),
            platform=os.environ.get(ENV_PLATFORM) or None,
        )


_initialized = False


def initialize(config: DistributedConfig | None = None) -> None:
    """Join (or form) the multi-host JAX runtime.

    Must run before any other JAX call in the process (backend
    initialization is one-shot).  Safe to call when the process is the
    whole cluster (num_processes in (None, 1) with no coordinator):
    becomes a no-op so single-host scripts run unchanged.
    """
    global _initialized
    import jax

    if _initialized:
        return
    config = config or DistributedConfig.from_env()

    # ONE truth for "will this process be part of a multi-process world":
    # the gloo-collectives config below and the world-formation skip must
    # agree, or a formed world ends up without cross-process collectives.
    # num_processes == 1 is single even WITH a coordinator address (an
    # elastic world that shrank to one worker): forming a one-process
    # distributed runtime buys no collectives and adds a shutdown barrier
    # that can hang on exit.
    single_process = config.num_processes == 1 or (
        config.num_processes is None and config.coordinator_address is None
    )
    multiprocess = not single_process

    if config.platform == "cpu" or config.local_device_count:
        # authoritative platform selection: env-var JAX_PLATFORMS can be
        # shadowed by experimental PJRT plugins, the config update cannot
        jax.config.update("jax_platforms", "cpu")
        if config.local_device_count:
            n = int(config.local_device_count)
            try:
                jax.config.update("jax_num_cpu_devices", n)
            except AttributeError:
                # older jax (<= 0.4.x) has no runtime option for the CPU
                # device count; fall back to the XLA flag, which is still
                # honored as long as the backends aren't up yet (true in a
                # fresh worker process — initialize() runs first).  A
                # stale count already in XLA_FLAGS is REPLACED — keeping
                # it would silently ignore the requested device count.
                import re

                prev = os.environ.get("XLA_FLAGS", "")
                flag = f"--xla_force_host_platform_device_count={n}"
                if "xla_force_host_platform_device_count" in prev:
                    new = re.sub(
                        r"--?xla_force_host_platform_device_count=\d+",
                        flag, prev,
                    )
                else:
                    new = (prev + " " + flag).strip()
                os.environ["XLA_FLAGS"] = new
        # cross-process CPU collectives need an explicit implementation —
        # but ONLY in a real multi-process world: the gloo factory needs a
        # distributed client, and a single-process world (which skips
        # jax.distributed bring-up below) would crash at backend creation
        if multiprocess:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    if not multiprocess:
        _initialized = True  # single-process: nothing to form
        return

    kwargs = {}
    if config.heartbeat_timeout_seconds is not None:
        import inspect

        sig = inspect.signature(jax.distributed.initialize)
        if "heartbeat_timeout_seconds" in sig.parameters:
            kwargs["heartbeat_timeout_seconds"] = config.heartbeat_timeout_seconds
        # else: older jax exposes no failure-detection knob — run with its
        # default timeout rather than refusing to form the world
    jax.distributed.initialize(
        coordinator_address=config.coordinator_address,
        num_processes=config.num_processes,
        process_id=config.process_id,
        **kwargs,
    )
    _initialized = True


def shutdown() -> None:
    global _initialized
    import jax

    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception as e:
            # best-effort teardown (peers may already be gone), but a
            # silent failure here has masked wedged-barrier bugs before
            log.debug("jax.distributed.shutdown failed: %s", e)
    _initialized = False


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_chief() -> bool:
    """True on the process that owns cluster-singleton work (checkpoint
    writes, stats export) — the Spark-driver role."""
    return process_index() == 0


def barrier(name: str = "dl4jtpu") -> None:
    """Block until every process reaches this point (device-level sync)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def put_global(arr, sharding, *, full_value: bool = False):
    """Assemble a global jax.Array from this process's host data.

    Single-process: plain device_put.  Multi-process, full_value=False:
    each process passes its LOCAL portion of a batch-sharded array (per-host
    input pipelines feed disjoint shards — the RDD-partition role) and the
    global shape is inferred by concatenation.  full_value=True: every
    process passes the SAME complete array (param placement), so the global
    shape is the array's own shape regardless of how the spec shards it —
    without this, a cross-host-sharded param would get a wrongly inflated
    inferred global shape.
    """
    import jax

    if arr is None:
        return None
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    import numpy as np

    arr = np.asarray(arr)
    if full_value:
        return jax.make_array_from_process_local_data(
            sharding, arr, global_shape=arr.shape
        )
    return jax.make_array_from_process_local_data(sharding, arr)


def fetch_global(arr):
    """Bring a (possibly non-addressable) global array fully to this host —
    the allgather needed before single-writer checkpoint/serialization of
    cross-host-sharded values."""
    import jax
    import numpy as np

    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


from deeplearning4j_tpu.data.iterator import DataSetIterator as _DataSetIterator


class DistributedDataSetIterator(_DataSetIterator):
    """Rank-strided view of a DataSetIterator: process k of N yields
    batches k, N+k, 2N+k, ... — the RDD-partition role for multi-host
    input pipelines (each host reads DISJOINT data; `put_global` then
    assembles the global batch from per-host shards).

    A ragged tail (total batches not divisible by world size) is DROPPED
    on every rank: each fit_batch is a cross-host collective, so unequal
    per-host step counts would wedge the slice on the last step.

    Wrap the SAME underlying iterator construction on every host:

        it = DistributedDataSetIterator(CsvIterator(...))
        model.fit(it)            # each host consumes its stride
    """

    def __init__(self, inner, rank: int | None = None,
                 world_size: int | None = None):
        self.inner = inner
        self.rank = process_index() if rank is None else rank
        self.world = process_count() if world_size is None else world_size
        self._consumed = False
        if not (0 <= self.rank < self.world):
            raise ValueError(f"rank {self.rank} outside world {self.world}")

    @property
    def batch_size(self):
        return getattr(self.inner, "batch_size", None)

    def _one_shot(self) -> bool:
        """True when the inner can serve exactly one pass (a generator:
        its own iterator, no reset)."""
        return not hasattr(self.inner, "reset") and iter(self.inner) is self.inner

    def __iter__(self):
        # a one-shot inner serves exactly ONE (possibly partial) pass;
        # starting a second would silently yield zero batches — or worse,
        # resume mid-stream after a partial pass
        if self._consumed and self._one_shot():
            raise NotImplementedError(
                f"{type(self.inner).__name__} is a one-shot iterator; wrap "
                "a resettable DataSetIterator (or a list) for multi-epoch use"
            )
        self._consumed = True          # armed at START: partial passes count
        # yield only from COMPLETE stride groups so every rank sees the
        # same step count (works for streaming inners of unknown length)
        group = []
        for batch in self.inner:
            group.append(batch)
            if len(group) == self.world:
                yield group[self.rank]
                group = []

    def reset(self) -> None:
        # fit() resets after EVERY epoch incl. the last; only an actual
        # second pass over a ONE-SHOT inner is an error (see __iter__)
        if hasattr(self.inner, "reset"):
            self.inner.reset()
            self._consumed = False
        elif not self._one_shot():     # re-iterable (e.g. a list)
            self._consumed = False
