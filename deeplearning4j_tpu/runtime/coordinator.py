"""Cluster control plane — membership, heartbeats, elastic restart.

Reference role: the Spark driver + `MeshOrganizer`/`ModelParameterServer`
pair owns cluster membership: executors handshake in, heartbeats detect
loss, and the fan-out tree is remodelled on join/leave (SURVEY.md §3.5,
§5.3).  On TPU the data plane (jax.distributed / GSPMD collectives —
`runtime.distributed`) fails whole-slice on any host loss, so the
TPU-native control plane's job is different in mechanism, identical in
capability: notice the loss fast, tear the generation down, and restart the
surviving world from the latest checkpoint.

Design: one `CoordinatorServer` (tiny JSON-lines-over-TCP service, stdlib
only — the gRPC-shaped role without a codegen dependency) plus a
`CoordinatorClient` per worker process:

  register(worker)   -> blocks until `expected` workers joined, returns
                        (generation, rank, world) — the membership barrier
                        that assigns jax.distributed process ids
  heartbeat(worker)  -> {generation, abort}; abort flips when any member
                        is evicted (missed heartbeats) or calls fail()
  report_ckpt(...)   -> single-writer checkpoint registry; survivors learn
                        the restore point for the next generation
  set_expected(n)    -> supervisor shrinks/grows the next generation
  push_metrics(...)  -> fleet telemetry ingestion: workers push registry
                        snapshots + traces; the FleetAggregator serves the
                        merged cluster view (observe/fleet.py)

Worker processes exit on abort (JAX's fail-the-world model); a supervisor
(`train.elastic.ElasticSupervisor`) respawns the new world.  The
kill-a-worker pytest in tests/test_distributed.py is the fault-injection
analog of the reference's dummy/delayed-transport tests (SURVEY.md §4.2).
"""

from __future__ import annotations

import json
import logging
import random
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from deeplearning4j_tpu.runtime import faults

log = logging.getLogger("deeplearning4j_tpu")


def _free_port(host: str = "127.0.0.1") -> int:
    """OS-assigned free TCP port (close-then-reuse; fine for local fleets)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _reserve_port(host: str) -> socket.socket:
    """Bind-and-hold a free port: the returned LISTENING socket keeps other
    processes off the port until we close it (SO_REUSEADDR so the next
    reservation isn't blocked by our own TIME_WAIT residue)."""
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, 0))
    s.listen(1)
    return s


# -- retry / backoff --------------------------------------------------------

class RetryExhausted(ConnectionError):
    """A CoordinatorClient op ran out of retry budget.  Carries the op and
    attempt count so the worker can exit with a control-plane-lost code the
    supervisor distinguishes from a real eviction."""

    def __init__(self, op: str, attempts: int, last: BaseException):
        super().__init__(
            f"coordinator op {op!r} failed after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}"
        )
        self.op = op
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    """Capped exponential backoff + jitter for one op class.

    `sleep` and `rand` are injectable so tests can run a patient budget
    without wall-clocking it (the `-m faults` group stays sub-second).
    Policies are stateless across calls — safe to share between clients.
    """

    #: transient shapes worth retrying: every socket-level failure
    #: (ConnectionError/timeout are OSError subclasses) plus a garbled
    #: half-written response from a server that died mid-reply
    RETRYABLE: tuple = (OSError, json.JSONDecodeError)

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.25,
                 sleep: Callable[[float], None] = time.sleep,
                 rand: Callable[[], float] = random.random):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self._sleep = sleep
        self._rand = rand

    def backoff(self, attempt: int) -> float:
        """Delay before attempt `attempt` (2-based): capped exponential
        with multiplicative jitter in [1-j, 1+j]."""
        raw = min(self.max_delay, self.base_delay * (2 ** (attempt - 2)))
        return raw * (1.0 + self.jitter * (2.0 * self._rand() - 1.0))

    def run(self, op: str, fn: Callable[[], Any],
            on_retry: Optional[Callable[[int, BaseException], None]] = None):
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if attempt > 1:
                if on_retry is not None:
                    on_retry(attempt, last)
                self._sleep(self.backoff(attempt))
            try:
                return fn()
            except self.RETRYABLE as e:
                last = e
        raise RetryExhausted(op, self.max_attempts, last) from last


def _send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


def _recv_json(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class CoordinatorServer:
    """Membership + heartbeat + checkpoint-registry service."""

    #: ledger ring size: a long-lived supervisor crosses many generations;
    #: the last 256 checkpoint reports / evictions are plenty for the
    #: supervisor's per-generation queries and status debugging
    LEDGER_CAP = 256

    def __init__(self, expected_workers: int, heartbeat_timeout: float = 10.0,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 10.0):
        self._lock = threading.Condition()
        self.expected = expected_workers
        self.heartbeat_timeout = heartbeat_timeout
        # per-request socket read timeout: a half-open client (SYN, then
        # silence — a worker killed mid-request) must not pin a handler
        # thread forever
        self.request_timeout = request_timeout
        # generation state
        self.generation = 0
        self.members: dict[str, dict[str, Any]] = {}   # id -> {rank, last_hb}
        self.abort = False
        self.pending: dict[str, dict[str, Any]] = {}   # joiners for next gen
        # checkpoint registry: latest wins; history is a bounded ring
        self.latest_ckpt: Optional[dict[str, Any]] = None
        self.history: deque[dict[str, Any]] = deque(maxlen=self.LEDGER_CAP)
        self._host = host
        self.jax_coordinator: Optional[str] = None
        # the NEXT generation's data-plane port, reserved (bound + listening)
        # from now until the seal hands it out — closing only at the seal
        # shrinks the steal window from "whole registration barrier" to the
        # worker's jax.distributed bring-up; a worker that still loses the
        # race exits non-zero and the supervisor respawns the generation
        self._port_hold: Optional[socket.socket] = _reserve_port(host)
        # eviction ledger: who actually failed, per generation (the signal
        # the supervisor shrinks on — collateral aborts of healthy peers,
        # which JAX's own coordination service causes by design, are not
        # evictions).  Bounded ring, same rationale as history.
        self.evictions: deque[dict[str, Any]] = deque(maxlen=self.LEDGER_CAP)

        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    self.connection.settimeout(outer.request_timeout)
                    req = _recv_json(self.rfile)
                    if req is None:
                        return
                    # register blocks in the membership barrier longer than
                    # any read should: lift the timeout for the RESPONSE
                    # write (reads are done at this point)
                    self.connection.settimeout(None)
                    resp = outer._dispatch(req)
                    _send_json(self.request, resp)
                except (OSError, json.JSONDecodeError):
                    # timeouts, resets, garbage — drop the request; the
                    # client's retry policy owns recovery
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = f"{host}:{self._server.server_address[1]}"
        self._threads = [
            threading.Thread(target=self._server.serve_forever, daemon=True),
            threading.Thread(target=self._monitor, daemon=True),
        ]
        self._stopped = False
        self._metrics_collector = None
        self._metrics_cleanup = None
        # fleet-wide telemetry: workers push registry snapshots + traces
        # (op "push_metrics"); the aggregator serves the merged cluster
        # view through the UIServer's /metrics/cluster + /api/trace/cluster
        from deeplearning4j_tpu.observe.fleet import FleetAggregator

        self.fleet = FleetAggregator()
        self._fleet_collector = None
        self._fleet_cleanup = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "CoordinatorServer":
        for t in self._threads:
            t.start()
        self._register_metrics()
        # fleet aggregation: skew/straggler gauges land in the LOCAL
        # registry (plain /metrics carries them) and the aggregator
        # becomes the process's active one (UIServer cluster endpoints)
        from deeplearning4j_tpu.observe import fleet as fleet_mod
        from deeplearning4j_tpu.observe.metrics import registry

        self._fleet_collector, self._fleet_cleanup = (
            self.fleet.make_collector()
        )
        registry().register_collector(self._fleet_collector)
        fleet_mod.set_active_aggregator(self.fleet)
        return self

    def stop(self) -> None:
        self._stopped = True
        if self._metrics_collector is not None:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().unregister_collector(self._metrics_collector)
            self._metrics_collector = None
            if self._metrics_cleanup is not None:
                # drop this server's series instead of freezing them at
                # their last values — a heartbeat-age alert must not stay
                # quiet because a dead coordinator still exports a small
                # stale age
                self._metrics_cleanup()
                self._metrics_cleanup = None
        if self._fleet_collector is not None:
            from deeplearning4j_tpu.observe import fleet as fleet_mod
            from deeplearning4j_tpu.observe.metrics import registry

            registry().unregister_collector(self._fleet_collector)
            self._fleet_collector = None
            if self._fleet_cleanup is not None:
                self._fleet_cleanup()
                self._fleet_cleanup = None
            fleet_mod.clear_active_aggregator(self.fleet)
        self._server.shutdown()
        self._server.server_close()
        if self._port_hold is not None:
            self._port_hold.close()
            self._port_hold = None

    def _register_metrics(self) -> None:
        """Publish cluster health into the telemetry spine: per-worker
        heartbeat age (the 'notice it fast' gauge — an alert on
        `heartbeat_age > timeout/2` fires BEFORE the eviction does),
        membership counts, generation, and the eviction total.  Pull
        style: gauges refresh at scrape time; an idle cluster costs
        nothing.  stop() unregisters the collector."""
        from deeplearning4j_tpu.observe.metrics import registry

        reg = registry()
        age = reg.gauge(
            "dl4jtpu_coordinator_heartbeat_age_seconds",
            "Seconds since each member's last heartbeat",
        )
        members = reg.gauge(
            "dl4jtpu_coordinator_members", "Sealed members this generation"
        )
        gen = reg.gauge(
            "dl4jtpu_coordinator_generation", "Current cluster generation"
        )
        evict = reg.counter(
            "dl4jtpu_coordinator_evictions_total", "Workers evicted"
        )

        seen: set = set()
        # concurrent scrapes (UIServer is threaded) run this collector
        # concurrently; the read-modify-write on `seen` must not interleave
        collect_lock = threading.Lock()

        def collect() -> None:
            if self._stopped:
                return
            now = time.time()
            with self._lock:
                ages = {
                    wid: now - m["last_hb"] for wid, m in self.members.items()
                }
                n, g, ev = len(self.members), self.generation, len(self.evictions)
            with collect_lock:
                # remove only THIS server's departed workers — clear()
                # would clobber series owned by another coordinator in
                # the process
                for wid in seen - set(ages):
                    age.remove(worker=wid)
                seen.clear()
                seen.update(ages)
                for wid, a in ages.items():
                    age.set(a, worker=wid)
            members.set(n)
            gen.set(g)
            evict.set_total(ev)

        def cleanup() -> None:
            with collect_lock:
                for wid in seen:
                    age.remove(worker=wid)
                seen.clear()
            members.set(0)

        self._metrics_collector = collect
        self._metrics_cleanup = cleanup
        reg.register_collector(collect)

    # -- request dispatch --------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "register":
            return self._register(req["worker"], req.get("info") or {})
        with self._lock:
            if op == "heartbeat":
                return self._heartbeat(req["worker"], req.get("step"))
            if op == "report_ckpt":
                entry = {"step": int(req["step"]), "path": req["path"],
                         "generation": self.generation,
                         "time": time.time()}
                self.latest_ckpt = entry
                self.history.append(entry)
                return {"ok": True}
            if op == "latest_ckpt":
                return {"ok": True, "ckpt": self.latest_ckpt}
            if op == "push_metrics":
                # fleet telemetry ingestion (the aggregator has its own
                # lock; it never takes this server's)
                self.fleet.ingest(req["worker"], req.get("payload") or {})
                return {"ok": True}
            if op == "fail":
                self._evict(req["worker"], reason=req.get("reason", "fail()"))
                return {"ok": True}
            if op == "leave":
                self.members.pop(req["worker"], None)
                return {"ok": True}
            if op == "set_expected":
                self.expected = int(req["n"])
                # workers may already be waiting in the membership barrier;
                # a lowered expectation can complete it right now
                self._maybe_seal()
                self._lock.notify_all()
                return {"ok": True}
            if op == "status":
                return {
                    "ok": True,
                    "generation": self.generation,
                    "abort": self.abort,
                    "members": sorted(self.members),
                    "expected": self.expected,
                    "ckpt": self.latest_ckpt,
                    "evictions": list(self.evictions),
                }
        return {"ok": False, "error": f"unknown op {op!r}"}

    # -- membership --------------------------------------------------------
    def _register(self, worker: str, info: dict) -> dict:
        """Membership barrier: blocks until `expected` workers are pending,
        then seals a new generation and assigns dense ranks."""
        with self._lock:
            if worker in self.members and not self.abort:
                # idempotent re-register: the worker's previous attempt was
                # sealed but the response got lost in transit — hand back
                # the existing assignment instead of queueing a ghost that
                # would wedge the next generation's barrier.  Refresh the
                # heartbeat too: the worker can't start beating until
                # register() returns, and the monitor must not evict a
                # reachable worker whose retries are still in flight.
                self.members[worker]["last_hb"] = time.time()
                return {
                    "ok": True,
                    "generation": self.generation,
                    "rank": self.members[worker]["rank"],
                    "world": len(self.members),
                    "members": sorted(self.members),
                    "jax_coordinator": self.jax_coordinator,
                    "ckpt": self.latest_ckpt,
                }
            self.pending[worker] = {"info": info, "time": time.time()}
            if not self._maybe_seal():
                # wait until a seal consumes our pending entry
                deadline = time.time() + 120.0
                while worker in self.pending:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self.pending.pop(worker, None)
                        return {"ok": False, "error": "registration timeout"}
                    self._lock.wait(timeout=min(remaining, 1.0))
            if worker not in self.members:
                return {"ok": False, "error": "evicted during registration"}
            return {
                "ok": True,
                "generation": self.generation,
                "rank": self.members[worker]["rank"],
                "world": len(self.members),
                "members": sorted(self.members),
                "jax_coordinator": self.jax_coordinator,
                "ckpt": self.latest_ckpt,
            }

    def _maybe_seal(self) -> bool:
        """With the lock held: if enough workers are pending, seal a new
        generation (assign dense ranks, fresh data-plane port).  Called on
        every registration AND on set_expected — lowering the expectation
        must be able to complete a barrier that is already waiting."""
        if not self.pending or len(self.pending) < self.expected:
            return False
        self.generation += 1
        self.abort = False
        # a fresh jax.distributed coordination-service port per generation
        # (the data-plane runtime cannot be rejoined on a stale port after
        # an abort).  The port was RESERVED (held listening) since the
        # previous seal; release it now — the last possible moment — and
        # immediately reserve the next generation's.
        hold, self._port_hold = self._port_hold, None
        if hold is not None:
            port = hold.getsockname()[1]
        else:                               # stop() raced us; fall back
            port = _free_port(self._host)
        self._port_hold = _reserve_port(self._host)
        if hold is not None:
            hold.close()
        self.jax_coordinator = f"{self._host}:{port}"
        now = time.time()
        # tpulint: disable=LK201 — every caller (register / set_expected
        # handlers, monitor loop) enters with self._lock held; the
        # notify_all() below would raise otherwise
        self.members = {}  # tpulint: disable=LK201
        for rank, wid in enumerate(sorted(self.pending)):
            self.members[wid] = {"rank": rank, "last_hb": now,  # tpulint: disable=LK201
                                 "info": self.pending[wid]["info"]}
        self.pending = {}  # tpulint: disable=LK201
        self._lock.notify_all()
        return True

    def _heartbeat(self, worker: str, step) -> dict:
        m = self.members.get(worker)
        if m is None:
            return {"ok": True, "generation": self.generation, "abort": True,
                    "evicted": True}
        m["last_hb"] = time.time()
        if step is not None:
            m["step"] = step
        return {"ok": True, "generation": self.generation, "abort": self.abort}

    def _evict(self, worker: str, reason: str) -> None:
        # caller (fail() handler, monitor sweep) holds self._lock — the
        # notify_all() below needs it
        if worker in self.members:
            del self.members[worker]  # tpulint: disable=LK201
            self.abort = True
            self.evictions.append(  # tpulint: disable=LK201
                {"generation": self.generation, "worker": worker,
                 "reason": reason, "time": time.time()}
            )
            self._lock.notify_all()

    def _monitor(self) -> None:
        while not self._stopped:
            time.sleep(min(self.heartbeat_timeout / 4, 0.5))
            now = time.time()
            with self._lock:
                dead = [
                    wid for wid, m in self.members.items()
                    if now - m["last_hb"] > self.heartbeat_timeout
                ]
                for wid in dead:
                    self._evict(wid, reason="heartbeat timeout")


def default_retry_policies(sleep: Callable[[float], None] = time.sleep
                           ) -> dict[str, RetryPolicy]:
    """Per-op retry budgets (ISSUE 3 control-plane hardening):

    - ``register`` is PATIENT: losing the membership barrier to one dropped
      packet costs a whole generation, so it gets the deepest budget.
    - ``heartbeat`` is SINGLE-TRY: it repeats every interval anyway, and the
      heartbeat thread already tolerates individual failures — retrying
      inside one beat would only delay the next one.
    - ``report_ckpt``/``leave`` (and the rest) are BOUNDED: useful to retry
      a few times, but the checkpoint on disk / process exit is the ground
      truth, so giving up is safe.
    """
    return {
        "register": RetryPolicy(max_attempts=8, base_delay=0.1,
                                max_delay=2.0, sleep=sleep),
        "heartbeat": RetryPolicy(max_attempts=1, sleep=sleep),
        "report_ckpt": RetryPolicy(max_attempts=4, base_delay=0.05,
                                   max_delay=1.0, sleep=sleep),
        "leave": RetryPolicy(max_attempts=3, base_delay=0.05,
                             max_delay=0.5, sleep=sleep),
        "*": RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=1.0,
                         sleep=sleep),
    }


class CoordinatorClient:
    """Worker-side stub. Every call is one short-lived TCP round trip —
    no long-lived connection to leak across fork/exec.

    Transient failures (refused/reset connections, read timeouts, a reply
    cut off mid-line) are retried per `default_retry_policies`; retries
    land on the telemetry spine as ``dl4jtpu_rpc_retries_total{op=...}``.
    Pass ``retry={...}`` to override budgets (tests inject a no-op sleep so
    patient budgets don't wall-clock), or ``retry={}``-with-missing-op to
    fall through to the ``"*"`` default.
    """

    def __init__(self, address: str, worker_id: str, timeout: float = 130.0,
                 retry: Optional[dict[str, RetryPolicy]] = None):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self.worker_id = worker_id
        self.timeout = timeout
        self._retry = default_retry_policies()
        if retry:
            self._retry.update(retry)

    def _rpc_once(self, obj: dict, timeout: Optional[float] = None) -> dict:
        faults.maybe_fail("coordinator.rpc")
        if timeout is None:
            timeout = self.timeout
        with socket.create_connection(self._addr, timeout=timeout) as s:
            _send_json(s, obj)
            # close the makefile wrapper explicitly: a GC'd-but-unclosed
            # wrapper raises ResourceWarning at an arbitrary later point
            # (pytest's unraisable collector pins it on innocent tests)
            with s.makefile("r") as f:
                resp = _recv_json(f)
        if resp is None:
            raise ConnectionError("coordinator closed connection")
        return resp

    def _rpc(self, obj: dict) -> dict:
        op = obj.get("op", "?")
        policy = self._retry.get(op) or self._retry["*"]
        if policy.max_attempts == 1:
            return self._rpc_once(obj)

        def on_retry(attempt, last):
            try:
                from deeplearning4j_tpu.observe.metrics import registry

                registry().counter("dl4jtpu_rpc_retries_total").inc(op=op)
            except Exception as e:
                # telemetry failure must never break the retry loop it
                # meters, but it should not vanish either
                log.debug("rpc retry metric failed: %s", e)

        return policy.run(op, lambda: self._rpc_once(obj), on_retry=on_retry)

    def register(self, info: dict | None = None) -> dict:
        r = self._rpc({"op": "register", "worker": self.worker_id, "info": info})
        if not r.get("ok"):
            raise RuntimeError(f"register failed: {r.get('error')}")
        return r

    def heartbeat(self, step: int | None = None) -> dict:
        faults.maybe_fail("heartbeat.send")
        return self._rpc({"op": "heartbeat", "worker": self.worker_id, "step": step})

    def report_ckpt(self, step: int, path: str) -> None:
        self._rpc({"op": "report_ckpt", "worker": self.worker_id,
                   "step": step, "path": path})

    #: push_metrics socket timeout: the push rides the HEARTBEAT thread,
    #: so a stalled transfer must fail fast — a heartbeat-starving push
    #: would get a healthy worker evicted for telemetry's sake
    PUSH_TIMEOUT_S = 5.0

    def push_metrics(self, payload: dict) -> None:
        """Push a fleet telemetry snapshot (observe.fleet.FleetReporter
        builds the payload) — SINGLE try, short socket timeout, same
        rationale as heartbeat: it repeats every interval anyway, losing
        one push is harmless (the next re-carries the totals), and the
        heartbeat thread it rides must never block minutes on a wedged
        transfer."""
        self._rpc_once({"op": "push_metrics", "worker": self.worker_id,
                        "payload": payload},
                       timeout=self.PUSH_TIMEOUT_S)

    def latest_ckpt(self) -> Optional[dict]:
        return self._rpc({"op": "latest_ckpt", "worker": self.worker_id}).get("ckpt")

    def fail(self, reason: str = "") -> None:
        self._rpc({"op": "fail", "worker": self.worker_id, "reason": reason})

    def leave(self) -> None:
        self._rpc({"op": "leave", "worker": self.worker_id})

    def status(self) -> dict:
        return self._rpc({"op": "status", "worker": self.worker_id})

    def set_expected(self, n: int) -> None:
        self._rpc({"op": "set_expected", "worker": self.worker_id, "n": n})
