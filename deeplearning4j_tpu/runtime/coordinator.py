"""Cluster control plane — membership, heartbeats, elastic restart.

Reference role: the Spark driver + `MeshOrganizer`/`ModelParameterServer`
pair owns cluster membership: executors handshake in, heartbeats detect
loss, and the fan-out tree is remodelled on join/leave (SURVEY.md §3.5,
§5.3).  On TPU the data plane (jax.distributed / GSPMD collectives —
`runtime.distributed`) fails whole-slice on any host loss, so the
TPU-native control plane's job is different in mechanism, identical in
capability: notice the loss fast, tear the generation down, and restart the
surviving world from the latest checkpoint.

Design: one `CoordinatorServer` (tiny JSON-lines-over-TCP service, stdlib
only — the gRPC-shaped role without a codegen dependency) plus a
`CoordinatorClient` per worker process:

  register(worker)   -> blocks until `expected` workers joined, returns
                        (generation, rank, world) — the membership barrier
                        that assigns jax.distributed process ids
  heartbeat(worker)  -> {generation, abort}; abort flips when any member
                        is evicted (missed heartbeats) or calls fail()
  report_ckpt(...)   -> single-writer checkpoint registry; survivors learn
                        the restore point for the next generation
  set_expected(n)    -> supervisor shrinks/grows the next generation

Worker processes exit on abort (JAX's fail-the-world model); a supervisor
(`train.elastic.ElasticSupervisor`) respawns the new world.  The
kill-a-worker pytest in tests/test_distributed.py is the fault-injection
analog of the reference's dummy/delayed-transport tests (SURVEY.md §4.2).
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Optional


def _free_port(host: str = "127.0.0.1") -> int:
    """OS-assigned free TCP port (close-then-reuse; fine for local fleets)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _send_json(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


def _recv_json(f) -> Optional[dict]:
    line = f.readline()
    if not line:
        return None
    return json.loads(line)


class CoordinatorServer:
    """Membership + heartbeat + checkpoint-registry service."""

    def __init__(self, expected_workers: int, heartbeat_timeout: float = 10.0,
                 host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Condition()
        self.expected = expected_workers
        self.heartbeat_timeout = heartbeat_timeout
        # generation state
        self.generation = 0
        self.members: dict[str, dict[str, Any]] = {}   # id -> {rank, last_hb}
        self.abort = False
        self.pending: dict[str, dict[str, Any]] = {}   # joiners for next gen
        # checkpoint registry: latest wins
        self.latest_ckpt: Optional[dict[str, Any]] = None
        self.history: list[dict[str, Any]] = []
        self._host = host
        self.jax_coordinator: Optional[str] = None
        # eviction ledger: who actually failed, per generation (the signal
        # the supervisor shrinks on — collateral aborts of healthy peers,
        # which JAX's own coordination service causes by design, are not
        # evictions)
        self.evictions: list[dict[str, Any]] = []

        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    req = _recv_json(self.rfile)
                    if req is None:
                        return
                    resp = outer._dispatch(req)
                    _send_json(self.request, resp)
                except (ConnectionError, json.JSONDecodeError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = f"{host}:{self._server.server_address[1]}"
        self._threads = [
            threading.Thread(target=self._server.serve_forever, daemon=True),
            threading.Thread(target=self._monitor, daemon=True),
        ]
        self._stopped = False
        self._metrics_collector = None
        self._metrics_cleanup = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "CoordinatorServer":
        for t in self._threads:
            t.start()
        self._register_metrics()
        return self

    def stop(self) -> None:
        self._stopped = True
        if self._metrics_collector is not None:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().unregister_collector(self._metrics_collector)
            self._metrics_collector = None
            if self._metrics_cleanup is not None:
                # drop this server's series instead of freezing them at
                # their last values — a heartbeat-age alert must not stay
                # quiet because a dead coordinator still exports a small
                # stale age
                self._metrics_cleanup()
                self._metrics_cleanup = None
        self._server.shutdown()
        self._server.server_close()

    def _register_metrics(self) -> None:
        """Publish cluster health into the telemetry spine: per-worker
        heartbeat age (the 'notice it fast' gauge — an alert on
        `heartbeat_age > timeout/2` fires BEFORE the eviction does),
        membership counts, generation, and the eviction total.  Pull
        style: gauges refresh at scrape time; an idle cluster costs
        nothing.  stop() unregisters the collector."""
        from deeplearning4j_tpu.observe.metrics import registry

        reg = registry()
        age = reg.gauge(
            "dl4jtpu_coordinator_heartbeat_age_seconds",
            "Seconds since each member's last heartbeat",
        )
        members = reg.gauge(
            "dl4jtpu_coordinator_members", "Sealed members this generation"
        )
        gen = reg.gauge(
            "dl4jtpu_coordinator_generation", "Current cluster generation"
        )
        evict = reg.counter(
            "dl4jtpu_coordinator_evictions_total", "Workers evicted"
        )

        seen: set = set()
        # concurrent scrapes (UIServer is threaded) run this collector
        # concurrently; the read-modify-write on `seen` must not interleave
        collect_lock = threading.Lock()

        def collect() -> None:
            if self._stopped:
                return
            now = time.time()
            with self._lock:
                ages = {
                    wid: now - m["last_hb"] for wid, m in self.members.items()
                }
                n, g, ev = len(self.members), self.generation, len(self.evictions)
            with collect_lock:
                # remove only THIS server's departed workers — clear()
                # would clobber series owned by another coordinator in
                # the process
                for wid in seen - set(ages):
                    age.remove(worker=wid)
                seen.clear()
                seen.update(ages)
                for wid, a in ages.items():
                    age.set(a, worker=wid)
            members.set(n)
            gen.set(g)
            evict.set_total(ev)

        def cleanup() -> None:
            with collect_lock:
                for wid in seen:
                    age.remove(worker=wid)
                seen.clear()
            members.set(0)

        self._metrics_collector = collect
        self._metrics_cleanup = cleanup
        reg.register_collector(collect)

    # -- request dispatch --------------------------------------------------
    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "register":
            return self._register(req["worker"], req.get("info") or {})
        with self._lock:
            if op == "heartbeat":
                return self._heartbeat(req["worker"], req.get("step"))
            if op == "report_ckpt":
                entry = {"step": int(req["step"]), "path": req["path"],
                         "generation": self.generation,
                         "time": time.time()}
                self.latest_ckpt = entry
                self.history.append(entry)
                return {"ok": True}
            if op == "latest_ckpt":
                return {"ok": True, "ckpt": self.latest_ckpt}
            if op == "fail":
                self._evict(req["worker"], reason=req.get("reason", "fail()"))
                return {"ok": True}
            if op == "leave":
                self.members.pop(req["worker"], None)
                return {"ok": True}
            if op == "set_expected":
                self.expected = int(req["n"])
                # workers may already be waiting in the membership barrier;
                # a lowered expectation can complete it right now
                self._maybe_seal()
                self._lock.notify_all()
                return {"ok": True}
            if op == "status":
                return {
                    "ok": True,
                    "generation": self.generation,
                    "abort": self.abort,
                    "members": sorted(self.members),
                    "expected": self.expected,
                    "ckpt": self.latest_ckpt,
                    "evictions": list(self.evictions),
                }
        return {"ok": False, "error": f"unknown op {op!r}"}

    # -- membership --------------------------------------------------------
    def _register(self, worker: str, info: dict) -> dict:
        """Membership barrier: blocks until `expected` workers are pending,
        then seals a new generation and assigns dense ranks."""
        with self._lock:
            self.pending[worker] = {"info": info, "time": time.time()}
            if not self._maybe_seal():
                # wait until a seal consumes our pending entry
                deadline = time.time() + 120.0
                while worker in self.pending:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        self.pending.pop(worker, None)
                        return {"ok": False, "error": "registration timeout"}
                    self._lock.wait(timeout=min(remaining, 1.0))
            if worker not in self.members:
                return {"ok": False, "error": "evicted during registration"}
            return {
                "ok": True,
                "generation": self.generation,
                "rank": self.members[worker]["rank"],
                "world": len(self.members),
                "members": sorted(self.members),
                "jax_coordinator": self.jax_coordinator,
                "ckpt": self.latest_ckpt,
            }

    def _maybe_seal(self) -> bool:
        """With the lock held: if enough workers are pending, seal a new
        generation (assign dense ranks, fresh data-plane port).  Called on
        every registration AND on set_expected — lowering the expectation
        must be able to complete a barrier that is already waiting."""
        if not self.pending or len(self.pending) < self.expected:
            return False
        self.generation += 1
        self.abort = False
        # a fresh jax.distributed coordination-service port per generation
        # (the data-plane runtime cannot be rejoined on a stale port after
        # an abort)
        self.jax_coordinator = f"{self._host}:{_free_port(self._host)}"
        now = time.time()
        self.members = {}
        for rank, wid in enumerate(sorted(self.pending)):
            self.members[wid] = {"rank": rank, "last_hb": now,
                                 "info": self.pending[wid]["info"]}
        self.pending = {}
        self._lock.notify_all()
        return True

    def _heartbeat(self, worker: str, step) -> dict:
        m = self.members.get(worker)
        if m is None:
            return {"ok": True, "generation": self.generation, "abort": True,
                    "evicted": True}
        m["last_hb"] = time.time()
        if step is not None:
            m["step"] = step
        return {"ok": True, "generation": self.generation, "abort": self.abort}

    def _evict(self, worker: str, reason: str) -> None:
        if worker in self.members:
            del self.members[worker]
            self.abort = True
            self.evictions.append(
                {"generation": self.generation, "worker": worker,
                 "reason": reason, "time": time.time()}
            )
            self._lock.notify_all()

    def _monitor(self) -> None:
        while not self._stopped:
            time.sleep(min(self.heartbeat_timeout / 4, 0.5))
            now = time.time()
            with self._lock:
                dead = [
                    wid for wid, m in self.members.items()
                    if now - m["last_hb"] > self.heartbeat_timeout
                ]
                for wid in dead:
                    self._evict(wid, reason="heartbeat timeout")


class CoordinatorClient:
    """Worker-side stub. Every call is one short-lived TCP round trip —
    no long-lived connection to leak across fork/exec."""

    def __init__(self, address: str, worker_id: str, timeout: float = 130.0):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self.worker_id = worker_id
        self.timeout = timeout

    def _rpc(self, obj: dict) -> dict:
        with socket.create_connection(self._addr, timeout=self.timeout) as s:
            _send_json(s, obj)
            # close the makefile wrapper explicitly: a GC'd-but-unclosed
            # wrapper raises ResourceWarning at an arbitrary later point
            # (pytest's unraisable collector pins it on innocent tests)
            with s.makefile("r") as f:
                resp = _recv_json(f)
        if resp is None:
            raise ConnectionError("coordinator closed connection")
        return resp

    def register(self, info: dict | None = None) -> dict:
        r = self._rpc({"op": "register", "worker": self.worker_id, "info": info})
        if not r.get("ok"):
            raise RuntimeError(f"register failed: {r.get('error')}")
        return r

    def heartbeat(self, step: int | None = None) -> dict:
        return self._rpc({"op": "heartbeat", "worker": self.worker_id, "step": step})

    def report_ckpt(self, step: int, path: str) -> None:
        self._rpc({"op": "report_ckpt", "worker": self.worker_id,
                   "step": step, "path": path})

    def latest_ckpt(self) -> Optional[dict]:
        return self._rpc({"op": "latest_ckpt", "worker": self.worker_id}).get("ckpt")

    def fail(self, reason: str = "") -> None:
        self._rpc({"op": "fail", "worker": self.worker_id, "reason": reason})

    def leave(self) -> None:
        self._rpc({"op": "leave", "worker": self.worker_id})

    def status(self) -> dict:
        return self._rpc({"op": "status", "worker": self.worker_id})

    def set_expected(self, n: int) -> None:
        self._rpc({"op": "set_expected", "worker": self.worker_id, "n": n})
