"""Deterministic RNG threading.

The reference's RNG is a stateful native generator shared through
NativeOps (SURVEY.md §2.1).  JAX RNG is functional: a SeedStream wraps a
root PRNG key and hands out named/folded subkeys so layer init and dropout
are reproducible and jit-safe.  Inside a compiled train step, per-step keys
are derived by folding the step counter into the stream key — no host
round-trip, no state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _stable_hash(name: str) -> int:
    # Python's hash() is salted per-process; use a stable FNV-1a instead so
    # named keys are reproducible across runs.
    h = 0xCBF29CE484222325
    for b in name.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h & 0x7FFFFFFF


class SeedStream:
    """Hands out independent subkeys from one root seed.

    - ``stream.key(name)`` — stable named key (layer init).
    - ``stream.next()`` — sequential key (ad-hoc host-side use).
    - ``SeedStream.fold(key, step)`` — derive a per-step key inside jit.
    """

    def __init__(self, seed: int | jax.Array = 0):
        import numpy as np

        if isinstance(seed, (jax.Array, np.ndarray)):
            if hasattr(seed, "dtype") and jnp.issubdtype(
                seed.dtype, jax.dtypes.prng_key
            ):
                self._key = seed
            elif seed.dtype == jnp.uint32:
                # old-style raw key array (jax.random.PRNGKey / a loaded
                # checkpoint's uint32 pair): normalize to a typed key NOW
                # — accepting it raw would defer the failure to
                # state_dict()'s key_data() call at checkpoint time
                self._key = jax.random.wrap_key_data(jnp.asarray(seed))
            else:
                raise TypeError(
                    "SeedStream seed array must be a typed PRNG key "
                    "(jax.random.key) or an old-style uint32 key array "
                    f"(jax.random.PRNGKey); got dtype {seed.dtype}"
                )
        else:
            self._key = jax.random.key(seed)
        self._count = 0

    @property
    def root(self) -> jax.Array:
        return self._key

    def key(self, name: str) -> jax.Array:
        return jax.random.fold_in(self._key, _stable_hash(name))

    def next(self) -> jax.Array:
        self._count += 1
        return jax.random.fold_in(self._key, self._count)

    # -- persistence: checkpoints must resume the SAME key sequence, or a
    # resumed run's dropout masks diverge from the uninterrupted run --
    def state_dict(self) -> dict:
        import numpy as np

        return {
            "key_data": np.asarray(jax.random.key_data(self._key)).tolist(),
            "count": self._count,
        }

    def load_state_dict(self, d: dict) -> None:
        import jax.numpy as jnp

        self._key = jax.random.wrap_key_data(
            jnp.asarray(d["key_data"], jnp.uint32))
        self._count = int(d["count"])

    @staticmethod
    def fold(key: jax.Array, step: jax.Array | int) -> jax.Array:
        return jax.random.fold_in(key, jnp.asarray(step, dtype=jnp.uint32))
