"""Compile-tax instrumentation — counts what the whole-program-compile
design pays for.

A whole-step-compiled stack lives or dies on amortizing compilation
(PROFILE.md: the compiled step sits at ~90% of roofline, so the headroom
is everything AROUND it).  This module keeps process-global counters of
the three compile taxes, fed by `jax.monitoring` events:

- **jit cache misses** (fresh traces): every distinct (function, shape,
  dtype) signature traced — the recompile tax a new sequence length or
  batch shape triggers.
- **backend compiles + compile seconds**: wall time inside XLA
  compilation (or persistent-cache retrieval, which rides the same
  event but costs milliseconds).
- **persistent cache hits / time saved**: programs served from the
  on-disk cache (`runtime/backend.py` enables it by default) instead of
  being recompiled.

Everything is cheap integers/floats behind one lock; listeners stay
registered for the process lifetime (jax.monitoring has no targeted
unregister).  Consumers take a `snapshot()` and subtract:

    before = compile_stats.snapshot()
    model.fit(data)
    spent = compile_stats.snapshot() - before
    print(spent.jit_cache_misses, spent.compile_secs)

`PerformanceListener` / `StatsListener` surface these per fit/record;
`Model.compile_stats()` adds the per-model distinct-program count.

These counters also feed the telemetry spine: `observe.metrics` bridges
every field into `dl4jtpu_compile_*` Prometheus families at scrape time
(see `observe.metrics._compile_stats_collector`), so ``GET /metrics`` on
the UIServer carries the compile taxes without any per-step push cost.
"""

from __future__ import annotations

import dataclasses
import threading

# jax.monitoring event names (stable since jax 0.4.x; see
# jax/_src/dispatch.py and jax/_src/compiler.py)
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_PUT_EVENT = "/jax/compilation_cache/cache_misses"
_CACHE_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"


@dataclasses.dataclass(frozen=True)
class CompileStats:
    """Immutable counter snapshot; subtract two for a window's delta."""

    jit_cache_misses: int = 0      # fresh traces (per jit signature)
    backend_compiles: int = 0      # XLA compile requests (incl. cache loads)
    compile_secs: float = 0.0      # wall seconds in compile/cache-retrieval
    persistent_cache_hits: int = 0  # programs loaded from the disk cache
    persistent_cache_puts: int = 0  # programs written to the disk cache
    compile_secs_saved: float = 0.0  # compile time the disk cache avoided

    @property
    def fresh_backend_compiles(self) -> int:
        """Compiles that actually ran XLA — requests NOT served from the
        persistent cache.  The warm-start criterion: a second process on a
        primed cache should show 0 here."""
        return self.backend_compiles - self.persistent_cache_hits

    def __sub__(self, other: "CompileStats") -> "CompileStats":
        return CompileStats(
            jit_cache_misses=self.jit_cache_misses - other.jit_cache_misses,
            backend_compiles=self.backend_compiles - other.backend_compiles,
            compile_secs=self.compile_secs - other.compile_secs,
            persistent_cache_hits=(
                self.persistent_cache_hits - other.persistent_cache_hits
            ),
            persistent_cache_puts=(
                self.persistent_cache_puts - other.persistent_cache_puts
            ),
            compile_secs_saved=(
                self.compile_secs_saved - other.compile_secs_saved
            ),
        )

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fresh_backend_compiles"] = self.fresh_backend_compiles
        d["compile_secs"] = round(d["compile_secs"], 4)
        d["compile_secs_saved"] = round(d["compile_secs_saved"], 4)
        return d


_lock = threading.Lock()
_counts = {
    "traces": 0,
    "compiles": 0,
    "compile_secs": 0.0,
    "hits": 0,
    "puts": 0,
    "saved_secs": 0.0,
}
_installed = False


def _on_event(event: str, **kwargs) -> None:
    if event == _CACHE_HIT_EVENT:
        with _lock:
            _counts["hits"] += 1
    elif event == _CACHE_PUT_EVENT:
        with _lock:
            _counts["puts"] += 1


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event == _TRACE_EVENT:
        with _lock:
            _counts["traces"] += 1
    elif event == _BACKEND_COMPILE_EVENT:
        with _lock:
            _counts["compiles"] += 1
            _counts["compile_secs"] += duration
    elif event == _CACHE_SAVED_EVENT:
        with _lock:
            _counts["saved_secs"] += max(0.0, duration)


def install() -> None:
    """Register the monitoring listeners (idempotent, process-global)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax.monitoring

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)


def snapshot() -> CompileStats:
    """Current process-global counters (installs listeners on first use —
    a snapshot taken before install() still subtracts cleanly: both ends
    of the window see the same zero baseline)."""
    install()
    with _lock:
        return CompileStats(
            jit_cache_misses=_counts["traces"],
            backend_compiles=_counts["compiles"],
            compile_secs=_counts["compile_secs"],
            persistent_cache_hits=_counts["hits"],
            persistent_cache_puts=_counts["puts"],
            compile_secs_saved=_counts["saved_secs"],
        )
