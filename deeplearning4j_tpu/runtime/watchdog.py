"""Step-deadline watchdog — hang detection for the fit loops.

A wedged step is the one failure PRs 2–3 made visible but not
survivable: a collective whose peer died, a device runtime that stopped
answering, a host sync that never returns.  Nothing times out until the
outer CI/job deadline, and the post-mortem shows nothing but a killed
process.  `StepWatchdog` closes that gap: the fit loops' `StepScope`
arms it around every dispatched step program (host_stage -> dispatch ->
device_sync -> listeners) and disarms on exit; the deadline is

    max(floor_s, k * EWMA(per-step latency) * n_steps)

so it tracks the model's real step time instead of a guessed constant
(``cold_floor_s`` substitutes while the EWMA has no sample yet — the
first step of a process legitimately spends minutes in XLA compilation).

Escalation ladder on a blown deadline:

  1. ``warn``        — structured log line +
                       ``dl4jtpu_watchdog_stalls_total{stage="warn"}``;
  2. ``stack_dump``  — `runtime/crash.write_hang_report()`: every
                       thread's current stack, so the report shows WHERE
                       the step wedged (collective, queue, lock) —
                       deliberately jax-free, the device runtime is
                       exactly what may be hung;
  3. ``abort``       — the ``abort`` callable.  Elastic workers pass
                       `exit_step_wedged` (``os._exit(EXIT_STEP_WEDGED)``,
                       no atexit — a wedged collective would hang the
                       shutdown barrier too) and `ElasticSupervisor`
                       respawns the generation WITHOUT shrinking the
                       world.  ``None`` (the default for plain fits)
                       stops the ladder after the stack dump.

One shared daemon monitor thread serves every watchdog in the process
(a thread per fitted model would leak one OS thread per model across a
long test suite); per-step cost is two lock acquires and one condition
notify — noise next to a dispatch.  Disabled entirely via
``flags.watchdog_enabled`` / ``DL4J_TPU_WATCHDOG=0``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("deeplearning4j_tpu")

#: exit code of a worker whose watchdog hit the abort stage — distinct
#: from an eviction (EXIT_MEMBERSHIP_CHANGED) and a control-plane loss
#: (EXIT_CONTROL_PLANE_LOST): the supervisor respawns the generation
#: without shrinking the world (the hardware wedged, the worker did not
#: fail its peers)
EXIT_STEP_WEDGED = 25

STAGES = ("warn", "stack_dump", "abort")


def exit_step_wedged(event: dict) -> None:
    """The elastic-worker abort action: leave the process immediately
    with the wedged exit code.  ``os._exit`` on purpose — atexit would
    run jax.distributed's shutdown barrier, which is wedged on the same
    dead peer the watchdog just diagnosed."""
    log.error("watchdog abort: step wedged, exiting %d", EXIT_STEP_WEDGED)
    os._exit(EXIT_STEP_WEDGED)


class _Monitor(threading.Thread):
    """ONE daemon thread serving every armed StepWatchdog in the
    process: waits until the earliest pending escalation across the
    armed set, fires it, re-sleeps.  An empty armed set parks the
    thread indefinitely (idle processes pay nothing)."""

    def __init__(self):
        super().__init__(name="dl4jtpu-watchdog", daemon=True)
        self.cond = threading.Condition()
        self.armed: set = set()
        # monotonic instant of the next scheduled re-check; arm() only
        # notifies when its deadline lands EARLIER — a notify per step
        # would context-switch this thread awake on every dispatch
        # (measured ~40% step overhead on ~1ms CPU steps)
        self.next_wake = float("-inf")

    def run(self) -> None:
        while True:
            ready = None
            with self.cond:
                timeout = None
                for wd in list(self.armed):
                    rel = wd._seconds_until_due()
                    if rel is None:
                        continue
                    if rel <= 0:
                        ready = wd
                        break
                    timeout = rel if timeout is None else min(timeout, rel)
                if ready is None:
                    self.next_wake = (
                        float("inf") if timeout is None
                        else time.monotonic() + timeout
                    )
                    self.cond.wait(timeout)
                    self.next_wake = float("-inf")   # awake: rescanning
                    continue
            # escalation side effects (report writes, the abort action)
            # run OUTSIDE the condition — poll() re-checks the token
            try:
                ready.poll()
            except BaseException:
                # a raising escalation action (e.g. an abort that calls
                # sys.exit — SystemExit only kills THIS thread) must not
                # take the process-wide monitor down with it: every
                # watchdog constructed so far holds a reference to this
                # thread and would keep arming into a dead one
                log.exception("watchdog escalation action raised")


_MONITOR: Optional[_Monitor] = None
_MONITOR_LOCK = threading.Lock()


def _monitor() -> _Monitor:
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is None or not _MONITOR.is_alive():
            _MONITOR = _Monitor()
            _MONITOR.start()
        return _MONITOR


class StepWatchdog:
    """Per-model step-deadline watchdog (see module docstring).

    floor_s / cold_floor_s: deadline floor with/without an EWMA sample
      (cold covers the first step's XLA compile).
    k: deadline multiplier over the per-step latency EWMA.
    dump_after / abort_after: stage-2/3 thresholds as multiples of the
      base deadline (warn fires at 1.0x).
    abort: callable(event_dict) for stage 3; None = stop after the dump.
    threaded: False detaches from the shared monitor — tests drive
      escalation deterministically via `poll(now=...)` with an injected
      clock.
    """

    def __init__(self, floor_s: float = 30.0, k: float = 10.0,
                 cold_floor_s: float = 600.0, ewma_alpha: float = 0.2,
                 dump_after: float = 1.5, abort_after: float = 2.0,
                 abort: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 threaded: bool = True, name: str = ""):
        self.floor_s = float(floor_s)
        self.cold_floor_s = max(float(cold_floor_s), self.floor_s)
        self.k = float(k)
        self.ewma_alpha = float(ewma_alpha)
        self.dump_after = float(dump_after)
        self.abort_after = float(abort_after)
        self.abort = abort
        self.name = name
        self.ewma: Optional[float] = None
        self.events: list[dict] = []
        self.report_paths: list[str] = []
        self._clock = clock
        self._mon = _monitor() if threaded else None
        self._cond = self._mon.cond if self._mon else threading.Condition()
        self._armed = False
        self._token = 0
        self._stage = 0
        self._t0 = 0.0
        self._base = self.cold_floor_s
        self._iteration = 0
        self._n_steps = 1
        self._stalls = None        # metrics family, resolved lazily

    # -- arm / disarm (the per-step hot path) ------------------------------
    def arm(self, iteration: int, n_steps: int = 1) -> None:
        with self._cond:
            self._token += 1
            self._armed = True
            self._stage = 0
            self._t0 = self._clock()
            per = self.ewma
            if per is None:
                self._base = self.cold_floor_s
            else:
                self._base = max(self.floor_s, self.k * per * max(1, n_steps))
            self._iteration = iteration
            self._n_steps = max(1, n_steps)
            if self._mon is not None:
                self._mon.armed.add(self)
                # wake the monitor ONLY when this deadline is earlier
                # than its next scheduled check (threaded watchdogs use
                # the monotonic clock, so the instants are comparable);
                # the common case — deadline ~30s out, monitor already
                # sleeping toward a similar instant — stays notify-free
                if self._t0 + self._base < self._mon.next_wake:
                    self._cond.notify_all()

    def disarm(self, dur: Optional[float] = None) -> None:
        """Step finished.  `dur` (seconds for the whole program) feeds
        the EWMA; pass None for failed steps — an aborted dispatch's
        wall time says nothing about healthy step latency.  A step the
        ladder escalated on is dropped for the same reason even when it
        eventually completed: folding a stall into the EWMA inflates
        every later deadline by ~k× the stall, masking the next genuine
        wedge."""
        with self._cond:
            self._armed = False
            self._token += 1
            escalated = self._stage > 0
            if self._mon is not None:
                self._mon.armed.discard(self)
            if dur is not None and dur >= 0 and not escalated:
                per = dur / self._n_steps
                a = self.ewma_alpha
                self.ewma = per if self.ewma is None else (
                    (1.0 - a) * self.ewma + a * per
                )

    def deadline_s(self) -> float:
        """The base deadline the NEXT arm() would get for n_steps=1."""
        per = self.ewma
        if per is None:
            return self.cold_floor_s
        return max(self.floor_s, self.k * per)

    # -- escalation --------------------------------------------------------
    def _seconds_until_due(self) -> Optional[float]:
        """Relative seconds until the next escalation stage, None when
        fully escalated or disarmed.  Caller holds the condition."""
        if not self._armed or self._stage >= len(STAGES):
            return None
        mult = (1.0, self.dump_after, self.abort_after)[self._stage]
        return self._t0 + self._base * mult - self._clock()

    def poll(self, now: Optional[float] = None) -> None:
        """Fire every escalation stage currently due.  The monitor
        thread calls this with the real clock; tests call it directly
        with a fake one."""
        while True:
            with self._cond:
                if not self._armed or self._stage >= len(STAGES):
                    return
                t = self._clock() if now is None else now
                mult = (1.0, self.dump_after, self.abort_after)[self._stage]
                if t < self._t0 + self._base * mult:
                    return
                stage = self._stage
                self._stage += 1
                token = self._token
                event = {
                    "stage": STAGES[stage],
                    "iteration": self._iteration,
                    "n_steps": self._n_steps,
                    "stalled_s": round(t - self._t0, 3),
                    "deadline_s": round(self._base, 3),
                    "step_ewma_s": self.ewma,
                    "time": time.time(),
                }
            self._fire(stage, event, token)

    def _fire(self, stage: int, event: dict, token: int) -> None:
        self.events.append(event)
        if len(self.events) > 64:
            del self.events[:-64]
        self._count(STAGES[stage])
        if stage == 0:
            log.warning(
                "WATCHDOG step %s stalled: %.3fs armed, deadline %.3fs "
                "(iteration %s, %d step(s) in program)",
                self.name or "program", event["stalled_s"],
                event["deadline_s"], event["iteration"], event["n_steps"],
            )
            return
        if stage == 1:
            from deeplearning4j_tpu.runtime import crash

            try:
                path = crash.write_hang_report(event)
                self.report_paths.append(path)
                log.error("WATCHDOG stack dump written to %s", path)
            except Exception:
                # diagnosing the hang must not crash the monitor thread
                log.exception("watchdog hang-report write failed")
            return
        # stage 2: abort — only if still armed with the same token (the
        # step may have finished while the report above was writing)
        with self._cond:
            live = self._armed and self._token == token
        if not live:
            return
        if self.abort is not None:
            log.error("WATCHDOG aborting wedged step: %s", event)
            self.abort(event)
        else:
            log.error(
                "WATCHDOG step wedged %.3fs past deadline and no abort "
                "action is configured; the process stays up (set one, or "
                "run under ElasticWorkerLoop for EXIT_STEP_WEDGED "
                "respawn)", event["stalled_s"] - event["deadline_s"],
            )

    def _count(self, stage: str) -> None:
        try:
            if self._stalls is None:
                from deeplearning4j_tpu.observe.metrics import registry

                self._stalls = registry().counter(
                    "dl4jtpu_watchdog_stalls_total"
                )
            self._stalls.inc(stage=stage)
        except Exception as e:
            # telemetry must never mask the stall handling itself
            log.debug("watchdog stall metric failed: %s", e)

    @property
    def stalled(self) -> bool:
        return bool(self.events)
