"""HBM OOM crash reporting — the `CrashReportingUtil` role.

The reference's distinctive failure UX (SURVEY.md §5.5): on OOM it writes a
detailed memory report (workspace sizes, last op) so users can act instead
of staring at an allocator stack trace.  TPU-native equivalent: on a
RESOURCE_EXHAUSTED from XLA, write a report with PJRT memory_stats and a
per-buffer attribution of every live jax.Array (shape/dtype/size/sharding,
largest first) — the buffers ARE the workspaces here.

Models call `maybe_write_oom_report(exc)` from their fit paths; users can
also call `write_memory_report(path)` any time.  Report location:
DL4JTPU_CRASH_DIR (default: cwd), mirroring the reference's
`crashDumpOutputDirectory`.
"""

from __future__ import annotations

import os
import time
from typing import Optional

ENV_CRASH_DIR = "DL4JTPU_CRASH_DIR"


def _live_buffer_table(limit: int = 60) -> tuple[list[str], int]:
    import jax

    rows = []
    total = 0
    try:
        arrays = jax.live_arrays()
    except Exception:
        return ["  <live-array introspection unavailable>"], 0
    sized = []
    for a in arrays:
        try:
            nbytes = a.size * a.dtype.itemsize
            sized.append((nbytes, a))
            total += nbytes
        except Exception:
            continue
    sized.sort(key=lambda t: -t[0])
    for nbytes, a in sized[:limit]:
        try:
            sh = getattr(a, "sharding", None)
            rows.append(
                f"  {nbytes/1e6:12.2f} MB  {str(a.dtype):>10}  "
                f"{str(a.shape):<24} {type(sh).__name__ if sh else ''}"
            )
        except Exception:
            continue
    if len(sized) > limit:
        rows.append(f"  ... and {len(sized) - limit} more buffers")
    return rows, total


def write_memory_report(path: Optional[str] = None,
                        header: str = "") -> str:
    """Write the device-memory report; returns the file path."""
    import jax

    if path is None:
        d = os.environ.get(ENV_CRASH_DIR, ".")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"dl4jtpu-memory-report-{int(time.time())}.txt")

    lines = ["deeplearning4j_tpu device memory report",
             f"time: {time.strftime('%Y-%m-%d %H:%M:%S')}", ""]
    if header:
        lines += [header, ""]
    for d in jax.local_devices():
        lines.append(f"device: {d} ({getattr(d, 'device_kind', d.platform)})")
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                  "largest_alloc_size", "num_allocs"):
            if k in stats:
                lines.append(f"  {k}: {stats[k]:,}")
        lines.append("")
    rows, total = _live_buffer_table()
    lines.append(f"live jax.Array buffers (largest first; {total/1e6:.1f} MB "
                 "total attributed):")
    lines.extend(rows)
    lines.append("")
    lines.append("hints: lower the batch size; enable rematerialization "
                 "(jax.checkpoint) on large blocks; shard params over more "
                 "chips (ParallelConfig(model=...)); use bf16_compute.")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def is_oom_error(exc: BaseException) -> bool:
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or (
        "Allocator" in msg and "OOM" in msg
    )


def maybe_write_oom_report(exc: BaseException) -> Optional[str]:
    """If exc looks like a device OOM, write the crash report and return its
    path (models re-raise the original error either way)."""
    if not is_oom_error(exc):
        return None
    try:
        return write_memory_report(
            header=f"TRIGGER: {type(exc).__name__}: {str(exc)[:2000]}"
        )
    except Exception:
        return None


import itertools as _itertools

_divergence_seq = _itertools.count()


def write_divergence_report(event: dict, path: Optional[str] = None) -> str:
    """Divergence report — the numeric-health analog of the OOM report.

    `observe.health.HealthListener` routes flagged events (NaN/Inf score,
    non-finite params, norm explosion) here: the structured event heads
    the same device-memory + live-buffer report an OOM produces, so the
    post-mortem has the params' residence and sizes next to the numbers
    that went bad.  Returns the report path.
    """
    import json

    if path is None:
        d = os.environ.get(ENV_CRASH_DIR, ".")
        os.makedirs(d, exist_ok=True)
        # timestamp + process-wide sequence: back-to-back events (the k
        # listener dispatches of a grouped program land in the same ms)
        # must not overwrite each other's reports
        path = os.path.join(
            d,
            f"dl4jtpu-divergence-report-{int(time.time() * 1000)}"
            f"-{next(_divergence_seq)}.txt",
        )
    header = "\n".join(
        ["DIVERGENCE EVENT (observe.health numeric monitor):"]
        + [f"  {k}: {v}" for k, v in sorted(event.items())]
        + ["", "event json: " + json.dumps(event, sort_keys=True)]
    )
    return write_memory_report(path, header=header)


_hang_seq = _itertools.count()


def write_hang_report(context: dict, path: Optional[str] = None) -> str:
    """Thread-stack dump for a wedged step (watchdog stage 2).

    Deliberately does NOT touch jax: the device runtime is exactly what
    may be hung, and a `memory_stats()` / `live_arrays()` call could
    block the watchdog thread too.  Pure host introspection: every
    thread's current stack via `sys._current_frames`, names/daemon
    flags, plus the watchdog's context (iteration, armed seconds,
    deadline).  Returns the report path.
    """
    import json
    import sys
    import threading
    import traceback

    if path is None:
        d = os.environ.get(ENV_CRASH_DIR, ".")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d,
            f"dl4jtpu-hang-report-{int(time.time() * 1000)}"
            f"-{next(_hang_seq)}.txt",
        )
    by_ident = {t.ident: t for t in threading.enumerate()}
    lines = [
        "deeplearning4j_tpu step-watchdog hang report",
        f"time: {time.strftime('%Y-%m-%d %H:%M:%S')}",
        "",
        "WATCHDOG EVENT:",
    ]
    lines += [f"  {k}: {v}" for k, v in sorted(context.items())]
    lines += ["", "event json: " + json.dumps(context, sort_keys=True,
                                              default=str), ""]
    frames = sys._current_frames()
    lines.append(f"threads ({len(frames)}):")
    for tid, frame in sorted(frames.items()):
        t = by_ident.get(tid)
        label = t.name if t is not None else "?"
        flags = " daemon" if (t is not None and t.daemon) else ""
        lines.append(f"-- thread {tid} ({label}{flags}):")
        for entry in traceback.format_stack(frame):
            lines.extend("  " + ln for ln in entry.rstrip().splitlines())
    lines.append("")
    lines.append(
        "hints: a stack inside a collective means a peer died mid-step "
        "(elastic respawn recovers); inside device_sync/block_until_ready "
        "means the device runtime stopped answering (check the PJRT "
        "transport); inside queue.get means the input pipeline stalled."
    )
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


class oom_report_scope:
    """Context manager the models wrap their compiled-step invocation in: a
    device OOM escaping the scope gets the memory report written and a
    pointer to it chained onto the error."""

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None:
            return False
        report = maybe_write_oom_report(exc)
        if report:
            raise RuntimeError(
                f"device OOM during fit step; memory report written to "
                f"{report}"
            ) from exc
        return False
