"""Backend discovery — the `Nd4jBackend` SPI role, TPU-native.

The reference selects an execution backend (nd4j-native CPU vs nd4j-cuda)
by classpath service discovery and routes every op through that backend's
OpExecutioner (SURVEY.md §1 L2, §2.2).  Here the "backend" is a PJRT
platform reported by JAX; ops never route through a host-side executioner —
whole computations are compiled — so the backend object only carries
identity, capability and preferred-dtype information.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os

import jax
import numpy as np

log = logging.getLogger("deeplearning4j_tpu")


@dataclasses.dataclass(frozen=True)
class Backend:
    """Identity + capabilities of the active PJRT platform."""

    platform: str                 # "tpu" | "cpu" | "gpu" | experimental names
    device_kind: str              # e.g. "TPU v5 lite"
    num_devices: int
    supports_bfloat16_matmul: bool

    @property
    def is_tpu(self) -> bool:
        # Experimental transports (e.g. the axon tunnel) still expose TPU
        # device kinds; detect by device kind as well as platform name.
        return self.platform == "tpu" or "TPU" in self.device_kind

    @property
    def compute_dtype(self):
        """Preferred matmul/conv dtype: bf16 on TPU (MXU-native), f32 on CPU."""
        return np.dtype("bfloat16") if self.supports_bfloat16_matmul else np.dtype("float32")


@functools.cache
def init_compile_cache() -> str | None:
    """Enable the persistent XLA compile cache by default (idempotent).

    Every user process otherwise recompiles its models from scratch —
    seconds to minutes of pure tax for programs XLA already built
    yesterday.  `__graft_entry__.py` set this up for the dryrun
    subprocess only; here it becomes the default for every run.

    Resolution order for the cache directory:
      1. an already-configured ``jax_compilation_cache_dir`` (config or
         the standard ``JAX_COMPILATION_CACHE_DIR`` env var) wins;
      2. ``DL4J_TPU_COMPILE_CACHE`` — a path, or ``0``/``off`` to skip
         enabling the default (it cannot un-configure a jax-level cache
         the user set explicitly);
      3. default: ``$XDG_CACHE_HOME/deeplearning4j_tpu/xla`` (falling
         back to ``~/.cache``).

    ``DL4J_TPU_CACHE_MIN_COMPILE_SECS`` overrides jax's persist
    threshold (default 1.0s: tiny programs recompile faster than disk
    round-trips; set 0 to persist everything, as the warm-start tests
    do).  Returns the active cache dir, or None when disabled.
    Hit/miss counts are observable via `runtime.compile_stats`.
    """
    from deeplearning4j_tpu.runtime import compile_stats

    compile_stats.install()          # count hits/misses from the first jit
    override = os.environ.get("DL4J_TPU_COMPILE_CACHE", "").strip()
    configured = jax.config.jax_compilation_cache_dir
    if configured:
        # explicit jax-level config wins — including over "off": this
        # function only ever ADDS a default, it never un-configures a
        # cache the user set up through jax itself
        path = configured
    elif override.lower() in ("0", "off", "false", "none"):
        return None
    elif override:
        path = override
    else:
        base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
            os.path.expanduser("~"), ".cache"
        )
        path = os.path.join(base, "deeplearning4j_tpu", "xla")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except OSError as exc:            # read-only home etc. — never fatal
        log.warning("persistent compile cache disabled (%s): %s", path, exc)
        return None
    min_secs = os.environ.get("DL4J_TPU_CACHE_MIN_COMPILE_SECS")
    if min_secs is not None:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_secs)
        )
    log.info("persistent XLA compile cache: %s", path)
    return path


@functools.cache
def backend() -> Backend:
    init_compile_cache()
    devs = jax.devices()
    d0 = devs[0]
    kind = getattr(d0, "device_kind", d0.platform)
    is_tpu_like = d0.platform == "tpu" or "TPU" in str(kind)
    return Backend(
        platform=d0.platform,
        device_kind=str(kind),
        num_devices=len(devs),
        supports_bfloat16_matmul=is_tpu_like,
    )


def devices():
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def platform() -> str:
    return backend().platform


def maxpool_fusion_barrier(x):
    """XLA:TPU workaround for a backward-pass mis-fusion: when a jitted
    program computes (producer -> reduce_window max), the compiler can
    fuse the pool's select-and-scatter transpose into the producer's
    transpose and emit NaN gradients (observed on the experimental axon
    TPU platform with conv 7x7/s2 SAME -> maxpool 3x3/s2 SAME; the same
    math split across two jits, or run eagerly, is finite — see
    tests/test_review_regressions.py).  An optimization barrier before
    the pool keeps the two patterns in separate fusions.  No-op off TPU,
    where the fusion is correct and the barrier would only inhibit it.
    """
    if backend().is_tpu:
        return jax.lax.optimization_barrier(x)
    return x
