"""Backend discovery — the `Nd4jBackend` SPI role, TPU-native.

The reference selects an execution backend (nd4j-native CPU vs nd4j-cuda)
by classpath service discovery and routes every op through that backend's
OpExecutioner (SURVEY.md §1 L2, §2.2).  Here the "backend" is a PJRT
platform reported by JAX; ops never route through a host-side executioner —
whole computations are compiled — so the backend object only carries
identity, capability and preferred-dtype information.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class Backend:
    """Identity + capabilities of the active PJRT platform."""

    platform: str                 # "tpu" | "cpu" | "gpu" | experimental names
    device_kind: str              # e.g. "TPU v5 lite"
    num_devices: int
    supports_bfloat16_matmul: bool

    @property
    def is_tpu(self) -> bool:
        # Experimental transports (e.g. the axon tunnel) still expose TPU
        # device kinds; detect by device kind as well as platform name.
        return self.platform == "tpu" or "TPU" in self.device_kind

    @property
    def compute_dtype(self):
        """Preferred matmul/conv dtype: bf16 on TPU (MXU-native), f32 on CPU."""
        return np.dtype("bfloat16") if self.supports_bfloat16_matmul else np.dtype("float32")


@functools.cache
def backend() -> Backend:
    devs = jax.devices()
    d0 = devs[0]
    kind = getattr(d0, "device_kind", d0.platform)
    is_tpu_like = d0.platform == "tpu" or "TPU" in str(kind)
    return Backend(
        platform=d0.platform,
        device_kind=str(kind),
        num_devices=len(devs),
        supports_bfloat16_matmul=is_tpu_like,
    )


def devices():
    return jax.devices()


def device_count() -> int:
    return len(jax.devices())


def platform() -> str:
    return backend().platform


def maxpool_fusion_barrier(x):
    """XLA:TPU workaround for a backward-pass mis-fusion: when a jitted
    program computes (producer -> reduce_window max), the compiler can
    fuse the pool's select-and-scatter transpose into the producer's
    transpose and emit NaN gradients (observed on the experimental axon
    TPU platform with conv 7x7/s2 SAME -> maxpool 3x3/s2 SAME; the same
    math split across two jits, or run eagerly, is finite — see
    tests/test_review_regressions.py).  An optimization barrier before
    the pool keeps the two patterns in separate fusions.  No-op off TPU,
    where the fusion is correct and the barrier would only inhibit it.
    """
    if backend().is_tpu:
        return jax.lax.optimization_barrier(x)
    return x
