"""Runtime substrate: device/backend discovery, mesh construction, flags, RNG.

Plays the role of the reference's L0/L1/L2 stack (libnd4j NativeOps ABI,
JavaCPP presets, Nd4jBackend SPI — see SURVEY.md §1) except that the kernels
themselves are supplied by XLA:TPU; what remains host-side is device
bootstrap, mesh topology, runtime flags and deterministic RNG.
"""

from deeplearning4j_tpu.runtime.backend import (
    Backend,
    backend,
    device_count,
    devices,
    init_compile_cache,
    platform,
)
from deeplearning4j_tpu.runtime.compile_stats import CompileStats
from deeplearning4j_tpu.runtime.coordinator import (
    CoordinatorClient,
    CoordinatorServer,
    RetryExhausted,
    RetryPolicy,
)
from deeplearning4j_tpu.runtime.faults import FaultPlan, InjectedFault
from deeplearning4j_tpu.runtime.distributed import DistributedConfig
from deeplearning4j_tpu.runtime.flags import Environment, environment
from deeplearning4j_tpu.runtime.mesh import MeshSpec, make_mesh, virtual_cpu_devices
from deeplearning4j_tpu.runtime.rng import SeedStream
from deeplearning4j_tpu.runtime.watchdog import EXIT_STEP_WEDGED, StepWatchdog

__all__ = [
    "CoordinatorClient",
    "CoordinatorServer",
    "RetryExhausted",
    "RetryPolicy",
    "FaultPlan",
    "InjectedFault",
    "DistributedConfig",
    "Backend",
    "backend",
    "CompileStats",
    "device_count",
    "devices",
    "init_compile_cache",
    "platform",
    "Environment",
    "environment",
    "MeshSpec",
    "make_mesh",
    "virtual_cpu_devices",
    "SeedStream",
    "EXIT_STEP_WEDGED",
    "StepWatchdog",
]
