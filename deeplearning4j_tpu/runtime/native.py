"""ctypes binding for the native IO runtime (native/dl4jtpu_io.cpp).

The reference's ETL hot paths are native (libnd4j buffer routines,
JavaCV-backed decoders behind DataVec — SURVEY.md §2.2); this module is
the TPU build's equivalent tier: CSV -> float32 matrices parsed
multithreaded in C++, IDX (MNIST-family) decoding, and uint8 -> float32
normalization at memory bandwidth.  Everything degrades gracefully — when
the shared library isn't built and can't be built (no toolchain), callers
fall back to their numpy paths.

    from deeplearning4j_tpu.runtime import native
    if native.available():
        arr = native.csv_read_f32("data.csv", skip_rows=1)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_NAME = "libdl4jtpu_io.so"
ENV_DISABLE = "DL4JTPU_NO_NATIVE"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """Attempt an in-place `make` (g++ is part of the supported toolchain).
    Announced via logging so a slow first call is explainable; skipped
    outright when the toolchain is missing."""
    import logging
    import shutil

    if shutil.which("make") is None or shutil.which(
        os.environ.get("CXX", "g++")
    ) is None:
        return False
    logging.getLogger(__name__).info(
        "building native IO library (one-time, %s)", _NATIVE_DIR
    )
    try:
        proc = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            capture_output=True, timeout=60,
        )
        return proc.returncode == 0
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get(ENV_DISABLE, "") not in ("", "0"):
            return None
        path = _NATIVE_DIR / _LIB_NAME
        if not path.exists() and not _build():
            return None
        if not path.exists():
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            return None
        lib.dl4jtpu_csv_read_f32.restype = ctypes.c_int
        lib.dl4jtpu_csv_read_f32.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
            ctypes.c_int,
        ]
        lib.dl4jtpu_idx_read_u8.restype = ctypes.c_int
        lib.dl4jtpu_idx_read_u8.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int), ctypes.c_long * 4,
        ]
        lib.dl4jtpu_u8_to_f32_scaled.restype = None
        lib.dl4jtpu_u8_to_f32_scaled.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_float, ctypes.c_float, ctypes.c_int,
        ]
        lib.dl4jtpu_free.restype = None
        lib.dl4jtpu_free.argtypes = [ctypes.c_void_p]
        lib.dl4jtpu_io_version.restype = ctypes.c_char_p
        try:
            lib.dl4jtpu_has_jpeg.restype = ctypes.c_int
            lib.dl4jtpu_jpeg_batch.restype = ctypes.c_int
            lib.dl4jtpu_jpeg_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_long,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int,
            ]
        except AttributeError:
            pass   # pre-1.1 library on disk; jpeg path reports unavailable
        try:
            lib.dl4jtpu_jpeg_batch_u8.restype = ctypes.c_int
            lib.dl4jtpu_jpeg_batch_u8.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_long,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
            ]
            lib._dl4jtpu_has_u8 = True
        except AttributeError:
            # pre-1.2 library: f32 decode works, uint8 wire path needs a
            # rebuild (make -C native) — jpeg_batch_decode raises clearly
            lib._dl4jtpu_has_u8 = False
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def version() -> Optional[str]:
    lib = _load()
    return lib.dl4jtpu_io_version().decode() if lib else None


def _n_threads() -> int:
    return max(1, min(8, os.cpu_count() or 1))


def csv_read_f32(path: str, delimiter: str = ",",
                 skip_rows: int = 0) -> np.ndarray:
    """Parse a numeric CSV into a float32 (rows, cols) array natively."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    data = ctypes.POINTER(ctypes.c_float)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.dl4jtpu_csv_read_f32(
        str(path).encode(), delimiter.encode()[:1], skip_rows,
        ctypes.byref(data), ctypes.byref(rows), ctypes.byref(cols),
        _n_threads(),
    )
    if rc != 0:
        raise IOError(f"dl4jtpu_csv_read_f32({path}) failed rc={rc}")
    try:
        out = np.ctypeslib.as_array(
            data, shape=(rows.value, cols.value)
        ).copy()
    finally:
        lib.dl4jtpu_free(data)
    return out


def idx_read_u8(path: str) -> np.ndarray:
    """Decode an IDX file of unsigned bytes (MNIST images/labels)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    data = ctypes.POINTER(ctypes.c_uint8)()
    ndim = ctypes.c_int()
    dims = (ctypes.c_long * 4)()
    rc = lib.dl4jtpu_idx_read_u8(
        str(path).encode(), ctypes.byref(data), ctypes.byref(ndim), dims
    )
    if rc != 0:
        raise IOError(f"dl4jtpu_idx_read_u8({path}) failed rc={rc}")
    shape = tuple(dims[i] for i in range(ndim.value))
    try:
        out = np.ctypeslib.as_array(data, shape=shape).copy()
    finally:
        lib.dl4jtpu_free(data)
    return out


def u8_to_f32_scaled(src: np.ndarray, scale: float = 1.0 / 255.0,
                     shift: float = 0.0) -> np.ndarray:
    """uint8 -> float32 * scale + shift (image normalization hot path)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    src = np.ascontiguousarray(src, dtype=np.uint8)
    dst = np.empty(src.shape, np.float32)
    lib.dl4jtpu_u8_to_f32_scaled(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.size, scale, shift, _n_threads(),
    )
    return dst


def has_jpeg() -> bool:
    """True when the library was compiled against libjpeg."""
    lib = _load()
    return bool(lib is not None and hasattr(lib, "dl4jtpu_has_jpeg")
                and lib.dl4jtpu_has_jpeg())


def jpeg_batch_decode(paths, height: int, width: int, channels: int = 3,
                      n_threads: int = 0, dtype=np.float32) -> np.ndarray:
    """Decode + resize a batch of JPEG files natively ->
    (n, height, width, channels) in 0..255 (the ImageRecordReader value
    convention).  libjpeg's DCT-domain prescale does most of the
    downscaling inside the IDCT; a bilinear pass lands the exact target.
    Files that fail to decode come back zero-filled (a warning is
    logged).

    dtype float32 (default) or uint8: uint8 is the WIRE format for the
    device-cast ETL path — 4x fewer host->device bytes, with the cast to
    the compute dtype running inside the jitted step."""
    import logging

    lib = _load()
    if lib is None or not has_jpeg():
        raise RuntimeError("native JPEG decode unavailable")
    dtype = np.dtype(dtype)
    if dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
        raise ValueError(f"jpeg_batch_decode dtype must be float32 or "
                         f"uint8, got {dtype}")
    paths = [str(p) for p in paths]
    n = len(paths)
    out = np.empty((n, height, width, channels), dtype)
    arr = (ctypes.c_char_p * n)(*(p.encode() for p in paths))
    if dtype == np.uint8:
        if not getattr(lib, "_dl4jtpu_has_u8", False):
            raise RuntimeError(
                "uint8 JPEG decode needs dl4jtpu_io >= 1.2 — rebuild the "
                "native library (make -C native)"
            )
        fails = lib.dl4jtpu_jpeg_batch_u8(
            arr, n, height, width, channels,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n_threads or _n_threads(),
        )
    else:
        fails = lib.dl4jtpu_jpeg_batch(
            arr, n, height, width, channels,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_threads or _n_threads(),
        )
    if fails:
        logging.getLogger(__name__).warning(
            "jpeg_batch_decode: %d/%d files failed (zero-filled)", fails, n
        )
    return out
