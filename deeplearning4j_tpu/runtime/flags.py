"""Runtime environment flags — the `Nd4j.getEnvironment()` role.

The reference centralizes runtime-mutable knobs (debug, verbose, NaN/Inf
panic profiling modes) in `Nd4j.getEnvironment()` / `ND4JSystemProperties`
(SURVEY.md §5.6, §5.1).  TPU-native, most correctness knobs map onto
jax.config switches; this module gives them one typed home plus env-var
initialization (prefix DL4J_TPU_*).
"""

from __future__ import annotations

import dataclasses
import os

import jax


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Environment:
    """Mutable runtime configuration.

    nan_panic mirrors the reference's OpExecutioner ProfilingMode.NAN_PANIC:
    enabling it flips jax_debug_nans so any NaN produced under jit raises.
    """

    debug: bool = False
    verbose: bool = False
    nan_panic: bool = False
    # Preferred training dtype for matmul/conv inputs; params stay f32.
    use_bfloat16_compute: bool = True
    # Shape-bucketing quantum for variable-length sequence batches
    # (recompilation hygiene, SURVEY.md §7.3 item 6).
    sequence_bucket_size: int = 64
    # Software-pipelined fit loop: how many batches the fit loops'
    # PrefetchIterator stages to device ahead of the running step
    # (background thread + bounded queue).  0 disables the wrap — every
    # batch is pulled and staged serially on the training thread, the
    # pre-pipelining behavior.
    prefetch_depth: int = 2
    # Device-compiled data pipeline (datavec/device.py): fit() lowers an
    # iterator's advertised transform chain into the step program and
    # stages raw uint8 bytes instead of host-decoded floats.  Off = the
    # advertising iterators always apply their transforms on the host.
    device_decode: bool = True
    # Step-deadline watchdog (runtime/watchdog.py): armed around every
    # dispatched step program; deadline = max(floor, k * EWMA of recent
    # per-step latency).  Disabled = no watchdog object is created at
    # fit entry (zero per-step cost).
    watchdog_enabled: bool = True
    watchdog_floor_s: float = 30.0
    watchdog_k: float = 10.0
    # ZeRO weight-update sharding stage for distribute()'s data-parallel
    # path (parallel/zero.py): 0 = replicated optimizer state + update
    # (the classic DP step), 1 = opt state and the update computation
    # sharded over the data axis (reduce-scatter grads -> per-shard
    # update -> all-gather params), 2 = ZeRO-1 plus persistently
    # sharded gradients.  ParallelConfig(zero=...) overrides.
    zero: int = 0
    # Autosharding planner (parallel/planner.py): when on, a bare
    # distribute(model) with no explicit ParallelConfig enumerates and
    # prices candidate placements (dispatch-free) and installs the
    # argmin — the same path as distribute(model, auto=True).
    auto_plan: bool = False

    def set_nan_panic(self, on: bool) -> None:
        self.nan_panic = on
        jax.config.update("jax_debug_nans", on)

    @staticmethod
    def from_env() -> "Environment":
        env = Environment(
            debug=_env_bool("DL4J_TPU_DEBUG"),
            verbose=_env_bool("DL4J_TPU_VERBOSE"),
            use_bfloat16_compute=_env_bool("DL4J_TPU_BF16", True),
            sequence_bucket_size=int(
                os.environ.get("DL4J_TPU_SEQUENCE_BUCKET", "64")
            ),
            prefetch_depth=int(
                os.environ.get("DL4J_TPU_PREFETCH_DEPTH", "2")
            ),
            device_decode=_env_bool("DL4J_TPU_DEVICE_DECODE", True),
            watchdog_enabled=_env_bool("DL4J_TPU_WATCHDOG", True),
            watchdog_floor_s=float(
                os.environ.get("DL4J_TPU_WATCHDOG_FLOOR", "30")
            ),
            watchdog_k=float(os.environ.get("DL4J_TPU_WATCHDOG_K", "10")),
            zero=int(os.environ.get("DL4J_TPU_ZERO", "0")),
            auto_plan=_env_bool("DL4J_TPU_AUTO_PLAN"),
        )
        if _env_bool("DL4J_TPU_NAN_PANIC"):
            env.set_nan_panic(True)
        return env


_ENV: Environment | None = None


def environment() -> Environment:
    global _ENV
    if _ENV is None:
        _ENV = Environment.from_env()
    return _ENV


def bucket_length(length: int, quantum: int | None = None) -> int:
    """Round a sequence length UP to the bucketing quantum.

    The recompile-hygiene primitive (SURVEY.md §7.3 item 6): a compiled
    step specializes on the time axis, so a mixed-length corpus fed at
    its raw lengths compiles one XLA program PER DISTINCT LENGTH.
    Rounding every batch's time axis up to a multiple of the quantum
    bounds the program count at ceil(max_len / quantum); masks carry
    which positions are real.  quantum=None reads
    ``environment().sequence_bucket_size``.
    """
    q = quantum if quantum is not None else environment().sequence_bucket_size
    if q <= 0:
        raise ValueError(f"bucket quantum must be positive, got {q}")
    n = max(1, int(length))
    return ((n + q - 1) // q) * q
