"""InceptionResNetV1 — the reference zoo's
`org.deeplearning4j.zoo.model.InceptionResNetV1` (the FaceNet backbone;
the reference's FaceNetNN1Small2 variant builds on the same blocks).

Stem, then scaled-residual inception blocks: A (35x35) / B (17x17) /
C (8x8) with Reduction-A/B in between.  Each block is a multi-branch
MergeVertex concat, 1x1-projected and added to its input through a
ScaleVertex (the 0.17/0.10/0.20 residual scales from the paper).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    GlobalPooling,
    InputType,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseOp,
    ElementWiseVertex,
    GraphBuilder,
    MergeVertex,
    ScaleVertex,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class InceptionResNetV1(ZooModel):
    NAME = "inception_resnet_v1"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 160, width: int = 160, channels: int = 3,
                 learning_rate: float = 1e-3,
                 blocks_a: int = 5, blocks_b: int = 10, blocks_c: int = 5,
                 embedding_size: int = 128):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate
        self.blocks_a, self.blocks_b, self.blocks_c = blocks_a, blocks_b, blocks_c
        self.embedding_size = embedding_size

    def _conv(self, g, name, inp, filters, kernel, stride=1, padding="same") -> str:
        g.add_layer(name, Conv2D(n_out=filters, kernel=(kernel, kernel),
                                 stride=(stride, stride), padding=padding,
                                 has_bias=False), inp)
        g.add_layer(f"{name}_bn", BatchNorm(activation=Activation.RELU), name)
        return f"{name}_bn"

    def _residual(self, g, name, inp, concat, out_channels, scale) -> str:
        g.add_layer(f"{name}_proj", Conv2D(n_out=out_channels, kernel=(1, 1)), concat)
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_proj")
        g.add_vertex(f"{name}_add", ElementWiseVertex(ElementWiseOp.ADD), inp, f"{name}_scale")
        g.add_layer(f"{name}_out", BatchNorm(activation=Activation.RELU), f"{name}_add")
        return f"{name}_out"

    def _block_a(self, g, name, inp) -> str:  # 35x35, 256ch in our stem
        b1 = self._conv(g, f"{name}_b1", inp, 32, 1)
        b2 = self._conv(g, f"{name}_b2b", self._conv(g, f"{name}_b2a", inp, 32, 1), 32, 3)
        b3a = self._conv(g, f"{name}_b3a", inp, 32, 1)
        b3 = self._conv(g, f"{name}_b3c", self._conv(g, f"{name}_b3b", b3a, 32, 3), 32, 3)
        g.add_vertex(f"{name}_cat", MergeVertex(), b1, b2, b3)
        return self._residual(g, name, inp, f"{name}_cat", 256, 0.17)

    def _block_b(self, g, name, inp) -> str:  # 17x17, 896ch
        b1 = self._conv(g, f"{name}_b1", inp, 128, 1)
        b2a = self._conv(g, f"{name}_b2a", inp, 128, 1)
        b2b = self._conv(g, f"{name}_b2b", b2a, 128, 1)   # (1x7)(7x1) folded to 1x1+3x3 pair
        b2 = self._conv(g, f"{name}_b2c", b2b, 128, 3)
        g.add_vertex(f"{name}_cat", MergeVertex(), b1, b2)
        return self._residual(g, name, inp, f"{name}_cat", 896, 0.10)

    def _block_c(self, g, name, inp) -> str:  # 8x8, 1792ch
        b1 = self._conv(g, f"{name}_b1", inp, 192, 1)
        b2a = self._conv(g, f"{name}_b2a", inp, 192, 1)
        b2 = self._conv(g, f"{name}_b2b", b2a, 192, 3)
        g.add_vertex(f"{name}_cat", MergeVertex(), b1, b2)
        return self._residual(g, name, inp, f"{name}_cat", 1792, 0.20)

    def _reduction_a(self, g, inp) -> str:  # 35 -> 17
        b1 = self._conv(g, "redA_b1", inp, 384, 3, stride=2, padding="valid")
        b2 = self._conv(g, "redA_b2c",
                        self._conv(g, "redA_b2b",
                                   self._conv(g, "redA_b2a", inp, 192, 1), 192, 3),
                        256, 3, stride=2, padding="valid")
        g.add_layer("redA_pool", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                             stride=(2, 2)), inp)
        g.add_vertex("redA_cat", MergeVertex(), b1, b2, "redA_pool")
        return "redA_cat"

    def _reduction_b(self, g, inp) -> str:  # 17 -> 8
        b1 = self._conv(g, "redB_b1b", self._conv(g, "redB_b1a", inp, 256, 1),
                        384, 3, stride=2, padding="valid")
        b2 = self._conv(g, "redB_b2b", self._conv(g, "redB_b2a", inp, 256, 1),
                        256, 3, stride=2, padding="valid")
        b3 = self._conv(g, "redB_b3c",
                        self._conv(g, "redB_b3b",
                                   self._conv(g, "redB_b3a", inp, 256, 1), 256, 3),
                        256, 3, stride=2, padding="valid")
        g.add_layer("redB_pool", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                             stride=(2, 2)), inp)
        g.add_vertex("redB_cat", MergeVertex(), b1, b2, b3, "redB_pool")
        return "redB_cat"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        )
        # stem: 160 -> 35-ish spatial, 256 channels
        cur = self._conv(g, "stem1", "input", 32, 3, stride=2, padding="valid")
        cur = self._conv(g, "stem2", cur, 32, 3, padding="valid")
        cur = self._conv(g, "stem3", cur, 64, 3)
        g.add_layer("stem_pool", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                             stride=(2, 2)), cur)
        cur = self._conv(g, "stem4", "stem_pool", 80, 1)
        cur = self._conv(g, "stem5", cur, 192, 3, padding="valid")
        cur = self._conv(g, "stem6", cur, 256, 3, stride=2, padding="valid")

        for i in range(self.blocks_a):
            cur = self._block_a(g, f"A{i}", cur)
        # Reduction-A concat: 384 + 256 + 256(pool) = 896 — the B-block width
        cur = self._reduction_a(g, cur)
        for i in range(self.blocks_b):
            cur = self._block_b(g, f"B{i}", cur)
        # Reduction-B concat: 384 + 256 + 256 + 896(pool) = 1792 — the C width
        cur = self._reduction_b(g, cur)
        for i in range(self.blocks_c):
            cur = self._block_c(g, f"C{i}", cur)

        g.add_layer("gap", GlobalPooling(pooling=PoolingType.AVG), cur)
        g.add_layer("drop", Dropout(rate=0.2), "gap")
        # bottleneck embedding (FaceNet's 128-d face embedding layer)
        g.add_layer("embedding", Dense(n_out=self.embedding_size,
                                       activation=Activation.IDENTITY), "drop")
        g.add_layer("output", OutputLayer(n_out=self.num_classes, loss=Loss.MCXENT,
                                          activation=Activation.SOFTMAX), "embedding")
        g.set_outputs("output")
        return g.build()
