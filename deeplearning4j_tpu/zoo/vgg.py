"""VGG16 / VGG19 — the reference zoo's VGG16/VGG19 (sequential stacks)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Conv2D,
    Dense,
    Dropout,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Nesterovs
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel

_VGG16_BLOCKS = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))
_VGG19_BLOCKS = ((64, 2), (128, 2), (256, 4), (512, 4), (512, 4))


class VGG16(ZooModel):
    NAME = "vgg16"
    BLOCKS = _VGG16_BLOCKS

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 learning_rate: float = 1e-2, fc_width: int = 4096):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate
        self.fc_width = fc_width

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Nesterovs(learning_rate=self.learning_rate, momentum=0.9))
            .weight_init(WeightInit.RELU)
            .activation(Activation.RELU)
            .list()
        )
        for filters, reps in self.BLOCKS:
            for _ in range(reps):
                b.layer(Conv2D(n_out=filters, kernel=(3, 3), padding="same"))
            b.layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
        b.layer(Dense(n_out=self.fc_width))
        b.layer(Dropout(rate=0.5))
        b.layer(Dense(n_out=self.fc_width))
        b.layer(Dropout(rate=0.5))
        b.layer(
            OutputLayer(n_out=self.num_classes, loss=Loss.MCXENT, activation=Activation.SOFTMAX)
        )
        b.set_input_type(InputType.convolutional(self.height, self.width, self.channels))
        return b.build()


class VGG19(VGG16):
    NAME = "vgg19"
    BLOCKS = _VGG19_BLOCKS
