"""Darknet19 — the reference zoo's `org.deeplearning4j.zoo.model.Darknet19`
(the YOLO2 backbone).

19 conv layers in the classic 3x3/1x1 alternating pattern, BatchNorm +
leaky-ReLU after every conv, five maxpool halvings, 1x1 class head +
global average pool.  All convs NHWC/bf16-friendly.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    GlobalPooling,
    InputType,
    LossLayer,
    NeuralNetConfiguration,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel

# (filters, kernel) per conv; "M" = maxpool.  Mirrors the darknet19 cfg.
DARKNET19_PLAN = [
    (32, 3), "M",
    (64, 3), "M",
    (128, 3), (64, 1), (128, 3), "M",
    (256, 3), (128, 1), (256, 3), "M",
    (512, 3), (256, 1), (512, 3), (256, 1), (512, 3), "M",
    (1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3),
]


def darknet_conv_block(b, idx: int, filters: int, kernel: int):
    """conv -> BN(leaky relu), the universal darknet block."""
    b.layer(Conv2D(name=f"conv{idx}", n_out=filters, kernel=(kernel, kernel),
                   padding="same", has_bias=False))
    b.layer(BatchNorm(name=f"bn{idx}", activation=Activation.LEAKYRELU))


class Darknet19(ZooModel):
    NAME = "darknet19"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .list()
        )
        idx, pools = 0, 0
        for item in DARKNET19_PLAN:
            if item == "M":
                pools += 1
                b.layer(Subsampling(name=f"pool{pools}", pooling=PoolingType.MAX,
                                    kernel=(2, 2), stride=(2, 2)))
            else:
                idx += 1
                darknet_conv_block(b, idx, item[0], item[1])
        # 1x1 class head then global average pool (darknet19 ordering)
        b.layer(Conv2D(name="head", n_out=self.num_classes, kernel=(1, 1), padding="same"))
        b.layer(GlobalPooling(name="gap", pooling=PoolingType.AVG))
        b.layer(LossLayer(name="output", loss=Loss.MCXENT, activation=Activation.SOFTMAX))
        return (
            b.set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
