"""NASNet-A — the reference zoo's `org.deeplearning4j.zoo.model.NASNet` [U].

NASNet-A (Mobile-shaped by default: 4 cells per stack, 44 cell filters →
1056 penultimate filters) built from the two learned cells:

  normal cell   — five add-pairs of {separable 3x3/5x5, avg pool, identity}
                  over (current h, previous p), concatenated with p
  reduction cell — stride-2 pairs of {separable 5x5/7x7, max/avg pool}
                  with two derived pairs, concatenated

Each separable branch is the doubled stage (relu → sepconv → bn, twice) of
the original; every cell starts by squeezing both inputs to the cell
filter count with 1x1 conv + BN.  One simplification, stated: when the
previous-cell activation has a larger spatial extent than the current one
(right after a reduction), it is adjusted with a strided 1x1 conv + BN
rather than the original's factorized space-shifted reduction — same
shapes, marginally less capacity.  Channels-last; the 1x1 squeezes and
pointwise halves of the separables are the MXU work.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dropout,
    GlobalPooling,
    InputType,
    OutputLayer,
    PoolingType,
    SeparableConv2D,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseOp,
    ElementWiseVertex,
    GraphBuilder,
    MergeVertex,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


def _relu():
    return ActivationLayer(activation=Activation.RELU)


class NASNet(ZooModel):
    NAME = "nasnet"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 cells_per_stack: int = 4, cell_filters: int = 44,
                 stem_filters: int = 32, learning_rate: float = 1e-3,
                 dropout: float = 0.0):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.cells_per_stack = cells_per_stack
        self.cell_filters = cell_filters
        self.stem_filters = stem_filters
        self.learning_rate = learning_rate
        self.dropout = dropout

    # -- cell building blocks ---------------------------------------------
    def _sep(self, g, name, inp, filters, kernel, stride=(1, 1)) -> str:
        """Doubled separable stage: (relu → sep k×k → bn) × 2, second
        stage always stride 1."""
        g.add_layer(f"{name}_r1", _relu(), inp)
        g.add_layer(f"{name}_s1", SeparableConv2D(
            n_out=filters, kernel=kernel, stride=stride, padding="same",
            has_bias=False), f"{name}_r1")
        g.add_layer(f"{name}_b1", BatchNorm(), f"{name}_s1")
        g.add_layer(f"{name}_r2", _relu(), f"{name}_b1")
        g.add_layer(f"{name}_s2", SeparableConv2D(
            n_out=filters, kernel=kernel, padding="same", has_bias=False),
            f"{name}_r2")
        g.add_layer(f"{name}_b2", BatchNorm(), f"{name}_s2")
        return f"{name}_b2"

    def _squeeze(self, g, name, inp, filters, stride=(1, 1)) -> str:
        g.add_layer(f"{name}_r", _relu(), inp)
        g.add_layer(f"{name}_c", Conv2D(n_out=filters, kernel=(1, 1),
                                        stride=stride, has_bias=False),
                    f"{name}_r")
        g.add_layer(f"{name}_b", BatchNorm(), f"{name}_c")
        return f"{name}_b"

    def _pool(self, g, name, inp, kind: PoolingType, stride) -> str:
        g.add_layer(name, Subsampling(pooling=kind, kernel=(3, 3),
                                      stride=stride, padding="same"), inp)
        return name

    def _add(self, g, name, a, b) -> str:
        g.add_vertex(name, ElementWiseVertex(ElementWiseOp.ADD), a, b)
        return name

    def _normal_cell(self, g, name, p, h, filters, adjust_prev: bool) -> str:
        h1 = self._squeeze(g, f"{name}_h", h, filters)
        p1 = self._squeeze(g, f"{name}_p", p, filters,
                           stride=(2, 2) if adjust_prev else (1, 1))
        x1 = self._add(g, f"{name}_x1",
                       self._sep(g, f"{name}_x1a", h1, filters, (5, 5)),
                       self._sep(g, f"{name}_x1b", p1, filters, (3, 3)))
        x2 = self._add(g, f"{name}_x2",
                       self._sep(g, f"{name}_x2a", p1, filters, (5, 5)),
                       self._sep(g, f"{name}_x2b", p1, filters, (3, 3)))
        x3 = self._add(g, f"{name}_x3",
                       self._pool(g, f"{name}_x3a", h1, PoolingType.AVG, (1, 1)),
                       p1)
        a4 = self._pool(g, f"{name}_x4a", p1, PoolingType.AVG, (1, 1))
        x4 = self._add(g, f"{name}_x4", a4, a4)
        x5 = self._add(g, f"{name}_x5",
                       self._sep(g, f"{name}_x5a", h1, filters, (3, 3)),
                       h1)
        g.add_vertex(f"{name}_out", MergeVertex(), p1, x1, x2, x3, x4, x5)
        return f"{name}_out"

    def _reduction_cell(self, g, name, p, h, filters, adjust_prev: bool) -> str:
        h1 = self._squeeze(g, f"{name}_h", h, filters)
        p1 = self._squeeze(g, f"{name}_p", p, filters,
                           stride=(2, 2) if adjust_prev else (1, 1))
        s2 = (2, 2)
        x1 = self._add(g, f"{name}_x1",
                       self._sep(g, f"{name}_x1a", h1, filters, (5, 5), s2),
                       self._sep(g, f"{name}_x1b", p1, filters, (7, 7), s2))
        x2 = self._add(g, f"{name}_x2",
                       self._pool(g, f"{name}_x2a", h1, PoolingType.MAX, s2),
                       self._sep(g, f"{name}_x2b", p1, filters, (7, 7), s2))
        x3 = self._add(g, f"{name}_x3",
                       self._pool(g, f"{name}_x3a", h1, PoolingType.AVG, s2),
                       self._sep(g, f"{name}_x3b", p1, filters, (5, 5), s2))
        x4 = self._add(g, f"{name}_x4",
                       self._pool(g, f"{name}_x4a", x1, PoolingType.AVG, (1, 1)),
                       x2)
        x5 = self._add(g, f"{name}_x5",
                       self._sep(g, f"{name}_x5a", x1, filters, (3, 3)),
                       self._pool(g, f"{name}_x5b", h1, PoolingType.MAX, s2))
        g.add_vertex(f"{name}_out", MergeVertex(), x2, x3, x4, x5)
        return f"{name}_out"

    # -- whole network -----------------------------------------------------
    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(
                InputType.convolutional(self.height, self.width, self.channels)
            )
        )
        g.add_layer("stem", Conv2D(n_out=self.stem_filters, kernel=(3, 3),
                                   stride=(2, 2), padding="same",
                                   has_bias=False), "input")
        g.add_layer("stem_bn", BatchNorm(), "stem")

        filters = self.cell_filters
        p, h = "stem_bn", "stem_bn"
        adjust = False                # p and h spatial extents differ?
        for stack in range(3):
            for i in range(self.cells_per_stack):
                cur = self._normal_cell(
                    g, f"s{stack}_n{i}", p, h, filters, adjust_prev=adjust
                )
                # after the cell, p and h are both post-reduction size
                p, h, adjust = h, cur, False
            if stack < 2:
                cur = self._reduction_cell(
                    g, f"s{stack}_red", p, h, filters * 2, adjust_prev=False
                )
                p, h = h, cur
                adjust = True          # next cell's p is pre-reduction size
                filters *= 2

        g.add_layer("head_relu", _relu(), h)
        g.add_layer("gap", GlobalPooling(pooling=PoolingType.AVG), "head_relu")
        if self.dropout:
            g.add_layer("head_drop", Dropout(rate=self.dropout), "gap")
            gap = "head_drop"
        else:
            gap = "gap"
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          loss=Loss.MCXENT,
                                          activation=Activation.SOFTMAX), gap)
        g.set_outputs("output")
        return g.build()
