"""TransformerEncoder — a DSL-built transformer for the zoo.

The reference has no transformer zoo entry; its attention surface stops at
`SelfAttentionLayer`/`AttentionVertex` configs (SURVEY.md §5.7).  This model
makes the TPU build's long-context story concrete: a decoder-style causal LM
(token embedding + positions + N pre-LN encoder blocks + per-token softmax)
whose attention blocks carry the `seq_parallel` knob — the SAME config runs
dense on one chip or ring/Ulysses-sharded over a "seq" mesh axis via
`distribute(model, ParallelConfig(seq=k))`.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Embedding,
    InputType,
    NeuralNetConfiguration,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.attention import (
    PositionalEncoding,
    TransformerEncoderBlock,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class TransformerEncoder(ZooModel):
    NAME = "transformer_encoder"

    def __init__(
        self,
        vocab_size: int = 1000,
        d_model: int = 128,
        n_heads: int = 4,
        n_layers: int = 2,
        d_ff: int = 0,
        causal: bool = True,
        seq_parallel: str = "none",
        seed: int = 123,
        learning_rate: float = 3e-4,
        moe_experts: int = 0,           # >0: MoE FFN layer after each block
        moe_top_k: int = 2,
        chunked_vocab_loss: bool = False,  # stream the vocab-xent in chunks
        vocab_chunk: int = 8192,
    ):
        super().__init__(vocab_size, seed)
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_layers = n_layers
        self.d_ff = d_ff
        self.causal = causal
        self.seq_parallel = seq_parallel
        self.learning_rate = learning_rate
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.chunked_vocab_loss = chunked_vocab_loss
        self.vocab_chunk = vocab_chunk

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(Embedding(n_in=self.vocab_size, n_out=self.d_model))
            .layer(PositionalEncoding())
        )
        for _ in range(self.n_layers):
            b.layer(
                TransformerEncoderBlock(
                    d_model=self.d_model,
                    n_heads=self.n_heads,
                    d_ff=self.d_ff,
                    causal=self.causal,
                    seq_parallel=self.seq_parallel,
                )
            )
            if self.moe_experts > 0:
                from deeplearning4j_tpu.nn.conf.moe import MoELayer

                b.layer(
                    MoELayer(
                        n_out=self.d_model,
                        n_experts=self.moe_experts,
                        top_k=self.moe_top_k,
                    )
                )
        if self.chunked_vocab_loss:
            # logits never materialize: the head streams vocab chunks
            # through the loss (ops/chunked_xent.py)
            from deeplearning4j_tpu.nn.conf import ChunkedSoftmaxOutputLayer

            head = ChunkedSoftmaxOutputLayer(
                n_out=self.vocab_size, chunk=self.vocab_chunk
            )
        else:
            head = RnnOutputLayer(
                n_out=self.vocab_size,
                loss=Loss.MCXENT,
                activation=Activation.SOFTMAX,
            )
        return (
            b.layer(head)
            .set_input_type(InputType.recurrent(1))
            .build()
        )
