"""ResNet-50 — the reference zoo's `org.deeplearning4j.zoo.model.ResNet50`
(BASELINE configs 2/5 architecture).

Bottleneck-v1 graph: conv7x7/2 + maxpool, then stages [3,4,6,3] of
1x1-3x3-1x1 bottlenecks with identity/projection shortcuts
(ElementWiseVertex.ADD — the reference models skips the same way), global
average pool, softmax.  BatchNorm after every conv.  NHWC throughout; at
batch 64+ the 3x3 convs dominate and map straight onto the MXU.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    GlobalPooling,
    InputType,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseOp,
    ElementWiseVertex,
    GraphBuilder,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class ResNet50(ZooModel):
    NAME = "resnet50"

    STAGES = (3, 4, 6, 3)
    FILTERS = (64, 128, 256, 512)

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def _bottleneck(self, g: GraphBuilder, name: str, inp: str, filters: int,
                    stride: int, project: bool) -> str:
        """1x1 (reduce) -> 3x3 -> 1x1 (expand x4) + shortcut."""
        expanded = filters * 4
        g.add_layer(f"{name}_c1", Conv2D(n_out=filters, kernel=(1, 1), stride=(stride, stride)), inp)
        g.add_layer(f"{name}_b1", BatchNorm(activation=Activation.RELU), f"{name}_c1")
        g.add_layer(f"{name}_c2", Conv2D(n_out=filters, kernel=(3, 3), padding="same"), f"{name}_b1")
        g.add_layer(f"{name}_b2", BatchNorm(activation=Activation.RELU), f"{name}_c2")
        g.add_layer(f"{name}_c3", Conv2D(n_out=expanded, kernel=(1, 1)), f"{name}_b2")
        g.add_layer(f"{name}_b3", BatchNorm(), f"{name}_c3")
        shortcut = inp
        if project:
            g.add_layer(f"{name}_sc", Conv2D(n_out=expanded, kernel=(1, 1), stride=(stride, stride)), inp)
            g.add_layer(f"{name}_sb", BatchNorm(), f"{name}_sc")
            shortcut = f"{name}_sb"
        g.add_vertex(f"{name}_add", ElementWiseVertex(ElementWiseOp.ADD), f"{name}_b3", shortcut)
        g.add_layer(f"{name}_out", _Relu(), f"{name}_add")
        return f"{name}_out"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(
                InputType.convolutional(self.height, self.width, self.channels)
            )
        )
        g.add_layer("stem_conv", Conv2D(n_out=64, kernel=(7, 7), stride=(2, 2), padding="same"), "input")
        g.add_layer("stem_bn", BatchNorm(activation=Activation.RELU), "stem_conv")
        g.add_layer(
            "stem_pool",
            Subsampling(pooling=PoolingType.MAX, kernel=(3, 3), stride=(2, 2), padding="same"),
            "stem_bn",
        )
        cur = "stem_pool"
        for stage, (blocks, filters) in enumerate(zip(self.STAGES, self.FILTERS)):
            for block in range(blocks):
                stride = 2 if (block == 0 and stage > 0) else 1
                project = block == 0
                cur = self._bottleneck(
                    g, f"s{stage}b{block}", cur, filters, stride, project
                )
        g.add_layer("avgpool", GlobalPooling(pooling=PoolingType.AVG), cur)
        g.add_layer(
            "output",
            OutputLayer(n_out=self.num_classes, loss=Loss.MCXENT, activation=Activation.SOFTMAX),
            "avgpool",
        )
        g.set_outputs("output")
        return g.build()


def _Relu():
    from deeplearning4j_tpu.nn.conf import ActivationLayer

    return ActivationLayer(activation=Activation.RELU)
