"""TextGenerationLSTM — the reference zoo's char-RNN (GravesLSTM stack,
BASELINE config 3 architecture): embedding-free one-hot chars ->
2x GravesLSTM -> per-timestep softmax."""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    GravesLSTM,
    InputType,
    NeuralNetConfiguration,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class TextGenerationLSTM(ZooModel):
    NAME = "textgenlstm"

    def __init__(self, vocab_size: int = 77, hidden: int = 200, seed: int = 123,
                 learning_rate: float = 1e-2, tbptt_length: int = 50):
        super().__init__(vocab_size, seed)
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.learning_rate = learning_rate
        self.tbptt_length = tbptt_length

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(GravesLSTM(n_out=self.hidden, activation=Activation.TANH))
            .layer(GravesLSTM(n_out=self.hidden, activation=Activation.TANH))
            .layer(
                RnnOutputLayer(
                    n_out=self.vocab_size,
                    loss=Loss.MCXENT,
                    activation=Activation.SOFTMAX,
                )
            )
            .set_input_type(InputType.recurrent(self.vocab_size))
        )
        if self.tbptt_length:
            b.tbptt(self.tbptt_length)
        return b.build()
