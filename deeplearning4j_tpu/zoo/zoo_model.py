"""ZooModel SPI — the `org.deeplearning4j.zoo.ZooModel` role.

Each zoo entry builds a ready-to-train model config for a named
architecture.  The reference's initPretrained() downloads checked-summed
weights; with no network, pretrained loading resolves from a local
directory ($DL4J_TPU_PRETRAINED_DIR) of ModelSerializer zips instead.
"""

from __future__ import annotations

import os
from pathlib import Path


class ZooModel:
    """Subclasses define conf() and NAME."""

    NAME = "zoo"

    def __init__(self, num_classes: int = 10, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def conf(self):
        raise NotImplementedError

    def init_model(self):
        """Build + init a fresh randomly-initialized model (ZooModel.init())."""
        from deeplearning4j_tpu.models.computation_graph import GraphModel
        from deeplearning4j_tpu.models.sequential import SequentialModel
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphConfiguration

        conf = self.conf()
        if isinstance(conf, GraphConfiguration):
            return GraphModel(conf).init()
        return SequentialModel(conf).init()

    def init_pretrained(self):
        """Load pretrained weights from the local pretrained directory."""
        root = Path(os.environ.get("DL4J_TPU_PRETRAINED_DIR", "~/.dl4j_tpu/models")).expanduser()
        path = root / f"{self.NAME}.zip"
        if not path.exists():
            raise FileNotFoundError(
                f"no pretrained weights for {self.NAME} at {path} "
                "(no-network environment: place ModelSerializer zips there)"
            )
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer

        return ModelSerializer.restore(str(path))
