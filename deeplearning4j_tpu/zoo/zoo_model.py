"""ZooModel SPI — the `org.deeplearning4j.zoo.ZooModel` role.

Each zoo entry builds a ready-to-train model config for a named
architecture.  The reference's initPretrained() downloads checked-summed
weights; with no network, pretrained loading resolves from the local
checksummed registry ($DL4JTPU_PRETRAINED_DIR — see zoo/pretrained.py)
of ModelSerializer zips instead.
"""

from __future__ import annotations


class ZooModel:
    """Subclasses define conf() and NAME."""

    NAME = "zoo"

    def __init__(self, num_classes: int = 10, seed: int = 123):
        self.num_classes = num_classes
        self.seed = seed

    def conf(self):
        raise NotImplementedError

    def init_model(self):
        """Build + init a fresh randomly-initialized model (ZooModel.init())."""
        from deeplearning4j_tpu.models.computation_graph import GraphModel
        from deeplearning4j_tpu.models.sequential import SequentialModel
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphConfiguration

        conf = self.conf()
        if isinstance(conf, GraphConfiguration):
            return GraphModel(conf).init()
        return SequentialModel(conf).init()

    def init_pretrained(self, pretrained_type: str = "default",
                        path: str | None = None):
        """Load pretrained weights (ZooModel.initPretrained(PretrainedType)).

        Resolution order: explicit `path` (a ModelSerializer zip), else the
        checksummed local registry (zoo/pretrained.py) keyed by
        (NAME, pretrained_type).
        """
        from deeplearning4j_tpu.train.checkpoint import ModelSerializer
        from deeplearning4j_tpu.zoo.pretrained import PretrainedRegistry

        if path is None:
            registry = PretrainedRegistry()
            try:
                path = registry.resolve(self.NAME, pretrained_type)
            except FileNotFoundError:
                # pre-registry layout: a bare {NAME}.zip (no checksum index).
                # Only for the default type, and only when this model has NO
                # registry entries — a typed request or a corrupted-registry
                # miss must surface, not silently serve whatever zip is lying
                # around
                legacy = registry.root / f"{self.NAME}.zip"
                if (
                    pretrained_type != "default"
                    or registry.available(self.NAME)
                    or not legacy.exists()
                ):
                    raise
                path = str(legacy)
        return ModelSerializer.restore(str(path))
