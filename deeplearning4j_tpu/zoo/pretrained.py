"""Pretrained-weights registry — `ZooModel.initPretrained()` +
`PretrainedType` roles (SURVEY.md §2.2 "Model zoo").

The reference downloads checksummed weight archives per (model,
PretrainedType).  This environment has no network, so the registry is a
local directory of ModelSerializer zips with the same integrity contract:
a `registry.json` index mapping (model, type) -> {file, sha256}, verified
on every load.  Weights are *registered* from local files (a training run,
a copied artifact) instead of downloaded — the API surface is otherwise
the reference's.

    registry = PretrainedRegistry()               # $DL4JTPU_PRETRAINED_DIR
    registry.register("resnet50", "imagenet", "/path/run_final.zip")
    model = ResNet50().init_pretrained("imagenet")
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from pathlib import Path
from typing import Optional

ENV_PRETRAINED_DIR = "DL4JTPU_PRETRAINED_DIR"
_LEGACY_ENV = "DL4J_TPU_PRETRAINED_DIR"      # pre-registry spelling
_DEFAULT_DIR = "~/.dl4j_tpu/models"


class ChecksumMismatchError(IOError):
    pass


def _sha256(path: str | Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class PretrainedRegistry:
    def __init__(self, root: Optional[str] = None):
        self.root = Path(
            root
            or os.environ.get(ENV_PRETRAINED_DIR)
            or os.environ.get(_LEGACY_ENV)
            or _DEFAULT_DIR
        ).expanduser()
        self.index_path = self.root / "registry.json"

    def _load_index(self) -> dict:
        if self.index_path.exists():
            return json.loads(self.index_path.read_text())
        return {}

    def _save_index(self, idx: dict) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        tmp = self.index_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(idx, indent=2, sort_keys=True))
        os.replace(tmp, self.index_path)

    def register(self, model_name: str, pretrained_type: str,
                 file_path: str) -> dict:
        """Copy a ModelSerializer zip into the registry under
        (model, type) and record its sha256."""
        src = Path(file_path)
        if not src.exists():
            raise FileNotFoundError(src)
        self.root.mkdir(parents=True, exist_ok=True)
        dest = self.root / f"{model_name}_{pretrained_type}.zip"
        if src.resolve() != dest.resolve():
            shutil.copyfile(src, dest)
        entry = {"file": dest.name, "sha256": _sha256(dest)}
        idx = self._load_index()
        idx.setdefault(model_name, {})[pretrained_type] = entry
        self._save_index(idx)
        return entry

    def available(self, model_name: Optional[str] = None) -> dict:
        idx = self._load_index()
        return idx.get(model_name, {}) if model_name else idx

    def resolve(self, model_name: str, pretrained_type: str) -> str:
        """Checksum-verified path for (model, type)."""
        idx = self._load_index()
        entry = idx.get(model_name, {}).get(pretrained_type)
        if entry is None:
            have = sorted(idx.get(model_name, {}))
            raise FileNotFoundError(
                f"no pretrained weights registered for {model_name!r} type "
                f"{pretrained_type!r} in {self.root} (registered: {have}). "
                "Register local weights with PretrainedRegistry().register()."
            )
        path = self.root / entry["file"]
        if not path.exists():
            raise FileNotFoundError(
                f"registry entry for {model_name}/{pretrained_type} points "
                f"at missing file {path}"
            )
        got = _sha256(path)
        if got != entry["sha256"]:
            raise ChecksumMismatchError(
                f"{path}: sha256 {got} != registered {entry['sha256']} — "
                "file corrupted or replaced; re-register it"
            )
        return str(path)
