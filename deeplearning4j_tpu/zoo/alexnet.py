"""AlexNet — the reference zoo's `org.deeplearning4j.zoo.model.AlexNet`.

Classic 5-conv/3-fc stack with LRN after the first two conv blocks
(Krizhevsky 2012, single-tower).  NHWC; the big early convs land on the
MXU as implicit GEMMs — no grouped two-GPU split (that was a 2012 memory
workaround, not an architecture feature).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Conv2D,
    Dense,
    Dropout,
    InputType,
    LocalResponseNormalization,
    NeuralNetConfiguration,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class AlexNet(ZooModel):
    NAME = "alexnet"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def conf(self):
        pool = lambda: Subsampling(pooling=PoolingType.MAX, kernel=(3, 3), stride=(2, 2))
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .activation(Activation.RELU)
            .list()
            .layer(Conv2D(name="c1", n_out=96, kernel=(11, 11), stride=(4, 4), padding="same"))
            .layer(LocalResponseNormalization(name="lrn1"))
            .layer(pool())
            .layer(Conv2D(name="c2", n_out=256, kernel=(5, 5), padding="same"))
            .layer(LocalResponseNormalization(name="lrn2"))
            .layer(pool())
            .layer(Conv2D(name="c3", n_out=384, kernel=(3, 3), padding="same"))
            .layer(Conv2D(name="c4", n_out=384, kernel=(3, 3), padding="same"))
            .layer(Conv2D(name="c5", n_out=256, kernel=(3, 3), padding="same"))
            .layer(pool())
            .layer(Dense(name="fc1", n_out=4096))
            .layer(Dropout(name="do1", rate=0.5))
            .layer(Dense(name="fc2", n_out=4096))
            .layer(Dropout(name="do2", rate=0.5))
            .layer(OutputLayer(name="output", n_out=self.num_classes,
                               loss=Loss.MCXENT, activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
