"""SqueezeNet v1.1 — the reference zoo's `org.deeplearning4j.zoo.model.SqueezeNet`.

Fire modules: 1x1 "squeeze" conv feeding parallel 1x1 + 3x3 "expand" convs
whose outputs concatenate on channels (MergeVertex).  Ends with a 1x1
class conv + global average pool — no big FC layers.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    Conv2D,
    Dropout,
    GlobalPooling,
    InputType,
    LossLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder, MergeVertex
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class SqueezeNet(ZooModel):
    NAME = "squeezenet"

    # (squeeze, expand) filters per fire module, v1.1 schedule
    FIRES = [(16, 64), (16, 64), (32, 128), (32, 128),
             (48, 192), (48, 192), (64, 256), (64, 256)]

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 224, width: int = 224, channels: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def _fire(self, g: GraphBuilder, name: str, inp: str, squeeze: int, expand: int) -> str:
        g.add_layer(f"{name}_sq", Conv2D(n_out=squeeze, kernel=(1, 1),
                                         activation=Activation.RELU), inp)
        g.add_layer(f"{name}_e1", Conv2D(n_out=expand, kernel=(1, 1),
                                         activation=Activation.RELU), f"{name}_sq")
        g.add_layer(f"{name}_e3", Conv2D(n_out=expand, kernel=(3, 3), padding="same",
                                         activation=Activation.RELU), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        )
        g.add_layer("stem", Conv2D(n_out=64, kernel=(3, 3), stride=(2, 2),
                                   activation=Activation.RELU, padding="same"), "input")
        g.add_layer("pool1", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                         stride=(2, 2)), "stem")
        cur = "pool1"
        for i, (s, e) in enumerate(self.FIRES, start=2):
            cur = self._fire(g, f"fire{i}", cur, s, e)
            if i in (3, 5):  # v1.1 pools after fire3 and fire5
                g.add_layer(f"pool{i}", Subsampling(pooling=PoolingType.MAX,
                                                    kernel=(3, 3), stride=(2, 2)), cur)
                cur = f"pool{i}"
        g.add_layer("drop", Dropout(rate=0.5), cur)
        g.add_layer("head", Conv2D(n_out=self.num_classes, kernel=(1, 1),
                                   activation=Activation.RELU), "drop")
        g.add_layer("gap", GlobalPooling(pooling=PoolingType.AVG), "head")
        g.add_layer("output", LossLayer(loss=Loss.MCXENT, activation=Activation.SOFTMAX), "gap")
        g.set_outputs("output")
        return g.build()
