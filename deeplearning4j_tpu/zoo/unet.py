"""UNet — the reference zoo's UNet (encoder-decoder with skip merges).

Exercises the graph machinery the other way from ResNet: MergeVertex
(channel concat) skip connections + Deconv2D upsampling, per-pixel
sigmoid output (segmentation).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Conv2D,
    InputType,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.layers import Deconv2D, LossLayer
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder, MergeVertex
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class UNet(ZooModel):
    NAME = "unet"

    def __init__(self, num_classes: int = 1, seed: int = 123,
                 height: int = 128, width: int = 128, channels: int = 3,
                 base_filters: int = 32, depth: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.base_filters = base_filters
        self.depth = depth
        self.learning_rate = learning_rate

    def _double_conv(self, g, name, inp, filters):
        g.add_layer(f"{name}_c1", Conv2D(n_out=filters, kernel=(3, 3), padding="same",
                                         activation=Activation.RELU), inp)
        g.add_layer(f"{name}_c2", Conv2D(n_out=filters, kernel=(3, 3), padding="same",
                                         activation=Activation.RELU), f"{name}_c1")
        return f"{name}_c2"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(
                InputType.convolutional(self.height, self.width, self.channels)
            )
        )
        # encoder
        skips = []
        cur = "input"
        for d in range(self.depth):
            filters = self.base_filters * (2**d)
            cur = self._double_conv(g, f"enc{d}", cur, filters)
            skips.append(cur)
            g.add_layer(f"down{d}", Subsampling(pooling=PoolingType.MAX,
                                                kernel=(2, 2), stride=(2, 2)), cur)
            cur = f"down{d}"
        # bottleneck
        cur = self._double_conv(g, "mid", cur, self.base_filters * (2**self.depth))
        # decoder
        for d in reversed(range(self.depth)):
            filters = self.base_filters * (2**d)
            g.add_layer(f"up{d}", Deconv2D(n_out=filters, kernel=(2, 2),
                                           stride=(2, 2)), cur)
            g.add_vertex(f"cat{d}", MergeVertex(), f"up{d}", skips[d])
            cur = self._double_conv(g, f"dec{d}", f"cat{d}", filters)
        g.add_layer("logits", Conv2D(n_out=self.num_classes, kernel=(1, 1)), cur)
        g.add_layer("output", LossLayer(loss=Loss.XENT), "logits")
        g.set_outputs("output")
        return g.build()
