"""SimpleCNN — the reference zoo's SimpleCNN (small 4-conv-block net)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class SimpleCNN(ZooModel):
    NAME = "simplecnn"

    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 48, width: int = 48, channels: int = 3,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .activation(Activation.RELU)
            .list()
        )
        for filters in (16, 32, 64, 128):
            b.layer(Conv2D(n_out=filters, kernel=(3, 3), padding="same"))
            b.layer(BatchNorm(activation=Activation.RELU))
            b.layer(Subsampling(pooling=PoolingType.MAX, kernel=(2, 2), stride=(2, 2)))
        b.layer(Dense(n_out=256))
        b.layer(Dropout(rate=0.5))
        b.layer(
            OutputLayer(n_out=self.num_classes, loss=Loss.MCXENT, activation=Activation.SOFTMAX)
        )
        b.set_input_type(InputType.convolutional(self.height, self.width, self.channels))
        return b.build()
