"""LeNet — the reference zoo's `org.deeplearning4j.zoo.model.LeNet`
(BASELINE config 1 architecture): conv20-pool-conv50-pool-dense500-softmax10."""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    Conv2D,
    Dense,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    Subsampling,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class LeNet(ZooModel):
    NAME = "lenet"

    def __init__(self, num_classes: int = 10, seed: int = 123,
                 height: int = 28, width: int = 28, channels: int = 1,
                 learning_rate: float = 1e-3):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.XAVIER)
            .activation(Activation.RELU)
            .list()
            .layer(Conv2D(n_out=20, kernel=(5, 5), stride=(1, 1), padding="same"))
            .layer(Subsampling(kernel=(2, 2), stride=(2, 2)))
            .layer(Conv2D(n_out=50, kernel=(5, 5), stride=(1, 1), padding="same"))
            .layer(Subsampling(kernel=(2, 2), stride=(2, 2)))
            .layer(Dense(n_out=500))
            .layer(
                OutputLayer(
                    n_out=self.num_classes,
                    loss=Loss.MCXENT,
                    activation=Activation.SOFTMAX,
                )
            )
            .set_input_type(
                InputType.convolutional(self.height, self.width, self.channels)
            )
            .build()
        )
