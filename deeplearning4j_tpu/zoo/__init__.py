"""Model zoo — the `org.deeplearning4j.zoo` role."""

from deeplearning4j_tpu.zoo.zoo_model import ZooModel
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.resnet import ResNet50
from deeplearning4j_tpu.zoo.simplecnn import SimpleCNN
from deeplearning4j_tpu.zoo.unet import UNet
from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19

__all__ = ["ZooModel", "LeNet", "ResNet50", "SimpleCNN", "UNet", "VGG16", "VGG19"]
