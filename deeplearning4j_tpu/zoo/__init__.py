"""Model zoo — the `org.deeplearning4j.zoo` role."""

from deeplearning4j_tpu.zoo.zoo_model import ZooModel
from deeplearning4j_tpu.zoo.alexnet import AlexNet
from deeplearning4j_tpu.zoo.darknet import Darknet19
from deeplearning4j_tpu.zoo.facenet import FaceNetNN4Small2
from deeplearning4j_tpu.zoo.nasnet import NASNet
from deeplearning4j_tpu.zoo.inception_resnet import InceptionResNetV1
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.resnet import ResNet50
from deeplearning4j_tpu.zoo.simplecnn import SimpleCNN
from deeplearning4j_tpu.zoo.squeezenet import SqueezeNet
from deeplearning4j_tpu.zoo.textgen import TextGenerationLSTM
from deeplearning4j_tpu.zoo.transformer import TransformerEncoder
from deeplearning4j_tpu.zoo.unet import UNet
from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19
from deeplearning4j_tpu.zoo.xception import Xception
from deeplearning4j_tpu.zoo.yolo import YOLO2, TinyYOLO

__all__ = [
    "ZooModel", "AlexNet", "Darknet19", "InceptionResNetV1", "LeNet",
    "ResNet50", "SimpleCNN", "SqueezeNet", "TextGenerationLSTM",
    "TransformerEncoder", "UNet", "VGG16", "VGG19", "Xception", "TinyYOLO",
    "YOLO2", "NASNet", "FaceNetNN4Small2",
]
