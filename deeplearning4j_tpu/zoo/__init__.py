"""Model zoo — the `org.deeplearning4j.zoo` role."""
