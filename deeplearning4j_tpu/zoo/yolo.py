"""TinyYOLO and YOLO2 — the reference zoo's `TinyYOLO` / `YOLO2` models.

TinyYOLO: the 9-conv tiny-darknet backbone + Yolo2OutputLayer, sequential.
YOLO2: the Darknet19 backbone with the 'passthrough' reorg — conv13's
26x26 features space-to-depth'd and concatenated with the 13x13 trunk
(SpaceToDepth + MergeVertex in the graph) — then the detection head.

Detection labels come from `nn.conf.objdetect.build_targets` (dense grid,
host-built); the loss is the fully-vectorized YOLOv2 loss compiled into
the training step.  Default anchors are the VOC anchors both reference
models ship with.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    Conv2D,
    InputType,
    NeuralNetConfiguration,
    PoolingType,
    SpaceToDepth,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder, MergeVertex
from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.darknet import DARKNET19_PLAN, darknet_conv_block
from deeplearning4j_tpu.zoo.zoo_model import ZooModel

# VOC anchor priors (grid units), as shipped with the reference models
TINY_YOLO_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                     (9.42, 5.11), (16.62, 10.52))
YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                 (7.88282, 3.52778), (9.77052, 9.16828))


class TinyYOLO(ZooModel):
    NAME = "tiny_yolo"

    FILTERS = (16, 32, 64, 128, 256, 512)

    def __init__(self, num_classes: int = 20, seed: int = 123,
                 height: int = 416, width: int = 416, channels: int = 3,
                 learning_rate: float = 1e-3, anchors=TINY_YOLO_ANCHORS):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate
        self.anchors = tuple(tuple(a) for a in anchors)

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .list()
        )
        for i, f in enumerate(self.FILTERS, start=1):
            darknet_conv_block(b, i, f, 3)
            # last pool is stride-1 'same' (keeps 13x13), darknet tiny quirk
            stride = (2, 2) if i < len(self.FILTERS) else (1, 1)
            b.layer(Subsampling(name=f"pool{i}", pooling=PoolingType.MAX,
                                kernel=(2, 2), stride=stride, padding="same"))
        darknet_conv_block(b, 7, 1024, 3)
        darknet_conv_block(b, 8, 1024, 3)
        head = len(self.anchors) * (5 + self.num_classes)
        b.layer(Conv2D(name="det_head", n_out=head, kernel=(1, 1), padding="same"))
        b.layer(Yolo2OutputLayer(name="yolo", anchors=self.anchors,
                                 num_classes=self.num_classes))
        return (
            b.set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )


class YOLO2(ZooModel):
    NAME = "yolo2"

    def __init__(self, num_classes: int = 20, seed: int = 123,
                 height: int = 416, width: int = 416, channels: int = 3,
                 learning_rate: float = 1e-3, anchors=YOLO2_ANCHORS):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate
        self.anchors = tuple(tuple(a) for a in anchors)

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        )
        # darknet19 backbone (without its class head), tracking the conv13
        # output (the 26x26 passthrough source)
        cur, idx, pools = "input", 0, 0
        passthrough = None
        for item in DARKNET19_PLAN:
            if item == "M":
                pools += 1
                name = f"pool{pools}"
                g.add_layer(name, Subsampling(pooling=PoolingType.MAX, kernel=(2, 2),
                                              stride=(2, 2)), cur)
                cur = name
            else:
                idx += 1
                g.add_layer(f"conv{idx}", Conv2D(n_out=item[0], kernel=(item[1], item[1]),
                                                 padding="same", has_bias=False), cur)
                g.add_layer(f"bn{idx}", BatchNorm(activation=Activation.LEAKYRELU), f"conv{idx}")
                cur = f"bn{idx}"
                if idx == 13:
                    passthrough = cur     # 26x26x512 before the last pool
        # detection trunk: two 3x3x1024 convs on the 13x13 map
        for j, name in ((19, "det1"), (20, "det2")):
            g.add_layer(name, Conv2D(n_out=1024, kernel=(3, 3), padding="same",
                                     has_bias=False), cur)
            g.add_layer(f"{name}_bn", BatchNorm(activation=Activation.LEAKYRELU), name)
            cur = f"{name}_bn"
        # passthrough: 1x1 squeeze then space-to-depth 26x26x64 -> 13x13x256
        g.add_layer("pt_conv", Conv2D(n_out=64, kernel=(1, 1), has_bias=False), passthrough)
        g.add_layer("pt_bn", BatchNorm(activation=Activation.LEAKYRELU), "pt_conv")
        g.add_layer("pt_s2d", SpaceToDepth(block=2), "pt_bn")
        g.add_vertex("concat", MergeVertex(), "pt_s2d", cur)
        g.add_layer("det3", Conv2D(n_out=1024, kernel=(3, 3), padding="same",
                                   has_bias=False), "concat")
        g.add_layer("det3_bn", BatchNorm(activation=Activation.LEAKYRELU), "det3")
        head = len(self.anchors) * (5 + self.num_classes)
        g.add_layer("det_head", Conv2D(n_out=head, kernel=(1, 1), padding="same"), "det3_bn")
        g.add_layer("yolo", Yolo2OutputLayer(anchors=self.anchors,
                                             num_classes=self.num_classes), "det_head")
        g.set_outputs("yolo")
        return g.build()
