"""Xception — the reference zoo's `org.deeplearning4j.zoo.model.Xception`.

Depthwise-separable convs throughout (SeparableConv2D), with residual
1x1-conv shortcuts around each block (entry flow 3 blocks, middle flow 8
identity blocks, exit flow).  Channels-last; the depthwise stage is
bandwidth-bound and the pointwise 1x1s are pure MXU GEMMs — the layout XLA
fuses best.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    GlobalPooling,
    InputType,
    OutputLayer,
    PoolingType,
    SeparableConv2D,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseOp,
    ElementWiseVertex,
    GraphBuilder,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


def _relu():
    return ActivationLayer(activation=Activation.RELU)


class Xception(ZooModel):
    NAME = "xception"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 299, width: int = 299, channels: int = 3,
                 learning_rate: float = 1e-3, middle_blocks: int = 8):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.learning_rate = learning_rate
        self.middle_blocks = middle_blocks

    def _sep_bn(self, g, name, inp, filters, relu_first: bool) -> str:
        cur = inp
        if relu_first:
            g.add_layer(f"{name}_r", _relu(), cur)
            cur = f"{name}_r"
        g.add_layer(f"{name}_sc", SeparableConv2D(n_out=filters, kernel=(3, 3),
                                                  padding="same", has_bias=False), cur)
        g.add_layer(f"{name}_bn", BatchNorm(), f"{name}_sc")
        return f"{name}_bn"

    def _entry_block(self, g, name, inp, filters, first_relu: bool) -> str:
        a = self._sep_bn(g, f"{name}_a", inp, filters, relu_first=first_relu)
        b = self._sep_bn(g, f"{name}_b", a, filters, relu_first=True)
        g.add_layer(f"{name}_pool", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                                stride=(2, 2), padding="same"), b)
        g.add_layer(f"{name}_proj", Conv2D(n_out=filters, kernel=(1, 1), stride=(2, 2),
                                           has_bias=False), inp)
        g.add_layer(f"{name}_projbn", BatchNorm(), f"{name}_proj")
        g.add_vertex(f"{name}_add", ElementWiseVertex(ElementWiseOp.ADD),
                     f"{name}_pool", f"{name}_projbn")
        return f"{name}_add"

    def _middle_block(self, g, name, inp) -> str:
        cur = inp
        for i in range(3):
            cur = self._sep_bn(g, f"{name}_{i}", cur, 728, relu_first=True)
        g.add_vertex(f"{name}_add", ElementWiseVertex(ElementWiseOp.ADD), cur, inp)
        return f"{name}_add"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(InputType.convolutional(self.height, self.width, self.channels))
        )
        g.add_layer("stem1", Conv2D(n_out=32, kernel=(3, 3), stride=(2, 2), has_bias=False), "input")
        g.add_layer("stem1_bn", BatchNorm(activation=Activation.RELU), "stem1")
        g.add_layer("stem2", Conv2D(n_out=64, kernel=(3, 3), has_bias=False), "stem1_bn")
        g.add_layer("stem2_bn", BatchNorm(activation=Activation.RELU), "stem2")

        cur = self._entry_block(g, "entry1", "stem2_bn", 128, first_relu=False)
        cur = self._entry_block(g, "entry2", cur, 256, first_relu=True)
        cur = self._entry_block(g, "entry3", cur, 728, first_relu=True)
        for m in range(self.middle_blocks):
            cur = self._middle_block(g, f"mid{m}", cur)

        # exit flow
        a = self._sep_bn(g, "exit_a", cur, 728, relu_first=True)
        b = self._sep_bn(g, "exit_b", a, 1024, relu_first=True)
        g.add_layer("exit_pool", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                             stride=(2, 2), padding="same"), b)
        g.add_layer("exit_proj", Conv2D(n_out=1024, kernel=(1, 1), stride=(2, 2),
                                        has_bias=False), cur)
        g.add_layer("exit_projbn", BatchNorm(), "exit_proj")
        g.add_vertex("exit_add", ElementWiseVertex(ElementWiseOp.ADD),
                     "exit_pool", "exit_projbn")
        c = self._sep_bn(g, "exit_c", "exit_add", 1536, relu_first=False)
        g.add_layer("exit_c_r", _relu(), c)
        d = self._sep_bn(g, "exit_d", "exit_c_r", 2048, relu_first=False)
        g.add_layer("exit_d_r", _relu(), d)
        g.add_layer("gap", GlobalPooling(pooling=PoolingType.AVG), "exit_d_r")
        g.add_layer("output", OutputLayer(n_out=self.num_classes, loss=Loss.MCXENT,
                                          activation=Activation.SOFTMAX), "gap")
        g.set_outputs("output")
        return g.build()
