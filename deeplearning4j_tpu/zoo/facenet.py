"""FaceNetNN4Small2 — the reference zoo's
`org.deeplearning4j.zoo.model.FaceNetNN4Small2` [U]: the OpenFace nn4.small2
inception variant producing L2-normalized 128-d face embeddings, trained
with the center-loss head (`CenterLossOutputLayer`).

GoogLeNet-style inception modules (1x1 / 3x3-reduce / 5x5-reduce / pool
branches merged on the channel axis); channels-last throughout so every
1x1 reduce is a pure MXU GEMM.  Embedding path: global avg pool → dense
128 → L2NormalizeVertex → center-loss softmax head.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf import (
    BatchNorm,
    CenterLossOutputLayer,
    Conv2D,
    Dense,
    GlobalPooling,
    InputType,
    LocalResponseNormalization,
    PoolingType,
    Subsampling,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (
    GraphBuilder,
    L2NormalizeVertex,
    MergeVertex,
)
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


def _conv_bn(g, name, inp, n_out, kernel, stride=(1, 1)) -> str:
    g.add_layer(
        name,
        Conv2D(n_out=n_out, kernel=kernel, stride=stride, padding="same",
               has_bias=False),
        inp,
    )
    g.add_layer(f"{name}_bn", BatchNorm(activation=Activation.RELU), name)
    return f"{name}_bn"


class FaceNetNN4Small2(ZooModel):
    NAME = "facenet_nn4_small2"

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 height: int = 96, width: int = 96, channels: int = 3,
                 embedding_size: int = 128, learning_rate: float = 1e-3,
                 center_alpha: float = 0.1, center_lambda: float = 2e-4):
        super().__init__(num_classes, seed)
        self.height, self.width, self.channels = height, width, channels
        self.embedding_size = embedding_size
        self.learning_rate = learning_rate
        self.center_alpha = center_alpha
        self.center_lambda = center_lambda

    def _inception(self, g, name, inp, *, b1, r3, b3, r5, b5, pool,
                   stride=(1, 1)) -> str:
        """Four-branch inception module; b1/b5/pool may be 0 to drop the
        branch (the nn4 reduction modules do)."""
        branches = []
        if b1:
            branches.append(_conv_bn(g, f"{name}_1x1", inp, b1, (1, 1), stride))
        red3 = _conv_bn(g, f"{name}_3r", inp, r3, (1, 1))
        branches.append(_conv_bn(g, f"{name}_3x3", red3, b3, (3, 3), stride))
        if b5:
            red5 = _conv_bn(g, f"{name}_5r", inp, r5, (1, 1))
            branches.append(_conv_bn(g, f"{name}_5x5", red5, b5, (5, 5), stride))
        g.add_layer(
            f"{name}_pool",
            Subsampling(pooling=PoolingType.MAX, kernel=(3, 3), stride=stride,
                        padding="same"),
            inp,
        )
        if pool:
            branches.append(
                _conv_bn(g, f"{name}_poolproj", f"{name}_pool", pool, (1, 1))
            )
        else:
            branches.append(f"{name}_pool")
        g.add_vertex(f"{name}_merge", MergeVertex(), *branches)
        return f"{name}_merge"

    def conf(self):
        g = (
            GraphBuilder()
            .seed(self.seed)
            .updater(Adam(self.learning_rate))
            .weight_init(WeightInit.RELU)
            .add_inputs("input")
            .set_input_types(
                InputType.convolutional(self.height, self.width, self.channels)
            )
        )
        cur = _conv_bn(g, "conv1", "input", 64, (7, 7), (2, 2))
        g.add_layer("pool1", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                         stride=(2, 2), padding="same"), cur)
        g.add_layer("lrn1", LocalResponseNormalization(), "pool1")
        cur = _conv_bn(g, "conv2", "lrn1", 64, (1, 1))
        cur = _conv_bn(g, "conv3", cur, 192, (3, 3))
        g.add_layer("lrn2", LocalResponseNormalization(), cur)
        g.add_layer("pool2", Subsampling(pooling=PoolingType.MAX, kernel=(3, 3),
                                         stride=(2, 2), padding="same"), "lrn2")

        cur = self._inception(g, "i3a", "pool2", b1=64, r3=96, b3=128,
                              r5=16, b5=32, pool=32)
        cur = self._inception(g, "i3b", cur, b1=64, r3=96, b3=128,
                              r5=32, b5=64, pool=64)
        cur = self._inception(g, "i3c", cur, b1=0, r3=128, b3=256,
                              r5=32, b5=64, pool=0, stride=(2, 2))
        cur = self._inception(g, "i4a", cur, b1=256, r3=96, b3=192,
                              r5=32, b5=64, pool=128)
        cur = self._inception(g, "i4e", cur, b1=0, r3=160, b3=256,
                              r5=64, b5=128, pool=0, stride=(2, 2))
        cur = self._inception(g, "i5a", cur, b1=256, r3=96, b3=384,
                              r5=0, b5=0, pool=96)
        cur = self._inception(g, "i5b", cur, b1=256, r3=96, b3=384,
                              r5=0, b5=0, pool=96)

        g.add_layer("gap", GlobalPooling(pooling=PoolingType.AVG), cur)
        g.add_layer("bottleneck", Dense(n_out=self.embedding_size,
                                        activation=Activation.IDENTITY), "gap")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer(
            "output",
            CenterLossOutputLayer(
                n_out=self.num_classes,
                alpha=self.center_alpha,
                lambda_coeff=self.center_lambda,
            ),
            "embeddings",
        )
        g.set_outputs("output")
        return g.build()
