"""Vocabulary cache — `org.deeplearning4j.models.word2vec.wordstore.VocabCache` role.

Word counts, index assignment (frequency-ordered), min-frequency filtering,
the unigram^0.75 negative-sampling table, and Huffman coding for
hierarchical softmax (batched-friendly: codes/points stored as padded
matrices so the HS loss is one gather + sigmoid under jit, not a per-node
tree walk).
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Iterable

import numpy as np


@dataclasses.dataclass
class VocabWord:
    word: str
    count: int
    index: int
    # Huffman path (hierarchical softmax): inner-node ids + branch codes
    codes: list[int] = dataclasses.field(default_factory=list)
    points: list[int] = dataclasses.field(default_factory=list)


class VocabCache:
    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency
        self._counter: Counter = Counter()
        self._words: dict[str, VocabWord] = {}
        self._by_index: list[VocabWord] = []
        self._ns_table: np.ndarray | None = None

    # -- building ----------------------------------------------------------
    def track(self, tokens: Iterable[str]) -> None:
        self._counter.update(tokens)

    def finish(self) -> "VocabCache":
        """Freeze: assign frequency-ordered indices, build Huffman codes."""
        kept = [
            (w, c) for w, c in self._counter.most_common()
            if c >= self.min_word_frequency
        ]
        self._words = {}
        self._by_index = []
        for i, (w, c) in enumerate(kept):
            vw = VocabWord(word=w, count=c, index=i)
            self._words[w] = vw
            self._by_index.append(vw)
        if self._by_index:
            self._build_huffman()
        return self

    def _build_huffman(self) -> None:
        """Standard word2vec Huffman tree over word counts; node ids index
        the inner-node (syn1) matrix."""
        n = len(self._by_index)
        # heap of (count, tiebreak, node_id); leaves 0..n-1, inner n..2n-2
        heap = [(vw.count, i, i) for i, vw in enumerate(self._by_index)]
        heapq.heapify(heap)
        parent = {}
        binary = {}
        next_id = n
        while len(heap) > 1:
            c1, _, a = heapq.heappop(heap)
            c2, _, b = heapq.heappop(heap)
            parent[a] = next_id
            parent[b] = next_id
            binary[a] = 0
            binary[b] = 1
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = heap[0][2] if heap else None
        for i, vw in enumerate(self._by_index):
            codes, points = [], []
            node = i
            while node != root:
                codes.append(binary[node])
                points.append(parent[node] - n)  # inner-node id, 0-based
                node = parent[node]
            vw.codes = codes[::-1]
            vw.points = points[::-1]

    # -- queries -----------------------------------------------------------
    def __contains__(self, word: str) -> bool:
        return word in self._words

    def __len__(self) -> int:
        return len(self._by_index)

    def word_for(self, index: int) -> str:
        return self._by_index[index].word

    def index_of(self, word: str) -> int:
        return self._words[word].index

    def word_frequency(self, word: str) -> int:
        return self._words[word].count if word in self._words else 0

    def words(self) -> list[str]:
        return [vw.word for vw in self._by_index]

    def total_word_count(self) -> int:
        return sum(vw.count for vw in self._by_index)

    # -- sampling tables ---------------------------------------------------
    def negative_table(self) -> np.ndarray:
        """Unigram^0.75 sampling distribution (word2vec's table)."""
        if self._ns_table is None:
            counts = np.array([vw.count for vw in self._by_index], dtype=np.float64)
            probs = counts**0.75
            self._ns_table = (probs / probs.sum()).astype(np.float64)
        return self._ns_table

    def subsample_keep_probs(self, t: float = 1e-3) -> np.ndarray:
        """word2vec frequent-word subsampling keep probability per index."""
        total = max(1, self.total_word_count())
        freq = np.array([vw.count for vw in self._by_index], dtype=np.float64) / total
        keep = np.minimum(1.0, np.sqrt(t / np.maximum(freq, 1e-12)) + t / np.maximum(freq, 1e-12))
        return keep

    def huffman_matrices(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(codes [V,L], points [V,L], mask [V,L]) padded to the max code
        length — the batched-HS layout."""
        max_len = max((len(vw.codes) for vw in self._by_index), default=0)
        v = len(self._by_index)
        codes = np.zeros((v, max_len), dtype=np.float32)
        points = np.zeros((v, max_len), dtype=np.int32)
        mask = np.zeros((v, max_len), dtype=np.float32)
        for i, vw in enumerate(self._by_index):
            l = len(vw.codes)
            codes[i, :l] = vw.codes
            points[i, :l] = vw.points
            mask[i, :l] = 1.0
        return codes, points, mask
