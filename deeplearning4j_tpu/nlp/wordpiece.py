"""BERT WordPiece tokenization + the BertIterator-role batch producer.

Reference: `org.deeplearning4j.text.tokenization.tokenizerfactory.
BertWordPieceTokenizerFactory` [U] (greedy longest-match-first WordPiece
against a BERT vocab.txt) and `org.deeplearning4j.iterator.BertIterator`
[U], which turns tokenized sentences into the fixed-shape
(token ids, attention mask, segment ids) batches BERT fine-tuning
consumes — BASELINE config 4's input pipeline.

TPU-native stance: tokenization is pure host-side Python (never traced);
the iterator emits STATIC-shape int batches (pad/truncate to max_len) so
the compiled fine-tune step never recompiles, with the attention mask
riding the DataSet features_mask channel our attention layers consume.
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class BasicTokenizer:
    """BERT's pre-tokenizer: clean, lowercase (optional), strip accents,
    split on whitespace and punctuation."""

    def __init__(self, lower_case: bool = True):
        self.lower_case = lower_case

    def tokenize(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
            text = unicodedata.normalize("NFD", text)
            text = "".join(c for c in text if unicodedata.category(c) != "Mn")
        out: List[str] = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif _is_punct(ch):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out


class BertWordPieceTokenizer:
    """Greedy longest-match-first WordPiece (BertWordPieceTokenizerFactory
    role).  vocab: token -> id mapping, or a vocab.txt path (one token per
    line, id = line number — the format BERT checkpoints ship)."""

    def __init__(self, vocab, *, lower_case: bool = True,
                 unk_token: str = "[UNK]", max_word_chars: int = 100):
        if isinstance(vocab, (str,)) or hasattr(vocab, "read"):
            vocab = self.load_vocab(vocab)
        self.vocab: dict = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.unk_token = unk_token
        self.max_word_chars = max_word_chars
        self._basic = BasicTokenizer(lower_case)

    @staticmethod
    def load_vocab(path_or_file) -> dict:
        close = False
        f = path_or_file
        if isinstance(path_or_file, str):
            f = open(path_or_file, encoding="utf-8")
            close = True
        try:
            return {line.rstrip("\r\n"): i for i, line in enumerate(f)}
        finally:
            if close:
                f.close()

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_word_chars:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for word in self._basic.tokenize(text):
            out.extend(self._wordpiece(word))
        return out

    def encode(self, text: str, pair: Optional[str] = None,
               *, max_len: int, add_special: bool = True):
        """(ids, mask, segment_ids) padded/truncated to max_len —
        [CLS] a... [SEP] b... [SEP] layout when add_special."""
        cls_id = self.vocab.get("[CLS]")
        sep_id = self.vocab.get("[SEP]")
        pad_id = self.vocab.get("[PAD]", 0)
        a = [self.vocab.get(t, self.vocab.get(self.unk_token, 0))
             for t in self.tokenize(text)]
        b = ([self.vocab.get(t, self.vocab.get(self.unk_token, 0))
              for t in self.tokenize(pair)] if pair else [])
        if add_special:
            if cls_id is None or sep_id is None:
                raise ValueError("vocab lacks [CLS]/[SEP] special tokens")
            budget = max_len - 2 - (1 if b else 0)
            if budget < (2 if b else 1):
                raise ValueError(
                    f"max_len={max_len} leaves no room for content after "
                    "the [CLS]/[SEP] special tokens"
                )
            # longest-first truncation (the BERT pair recipe)
            while len(a) + len(b) > budget:
                (a if len(a) >= len(b) else b).pop()
            ids = [cls_id] + a + [sep_id] + (b + [sep_id] if b else [])
            seg = [0] * (2 + len(a)) + [1] * (len(b) + 1 if b else 0)
        else:
            ids = (a + b)[:max_len]
            seg = [0] * len(ids)
        mask = [1] * len(ids)
        pad = max_len - len(ids)
        return (
            np.asarray(ids + [pad_id] * pad, np.int32),
            np.asarray(mask + [0] * pad, np.float32),
            np.asarray(seg + [0] * pad, np.int32),
        )


class BertIterator(DataSetIterator):
    """Fixed-shape BERT fine-tune batches (BertIterator role): sentences
    (+ optional pairs) with integer labels -> DataSet batches whose
    features are token ids, features_mask is the attention mask, labels
    one-hot.  Static shapes: every batch pads to (batch_size, max_len).

    dynamic_seq_len=True enables SEQUENCE BUCKETING: examples are grouped
    by tokenized length and each batch's time axis is the group's length
    rounded UP to the bucket quantum (`bucket_size`, default
    `environment().sequence_bucket_size`), capped at max_len.  A
    mixed-length corpus then compiles at most ceil(max_len / quantum)
    distinct step programs instead of one per distinct length, and short
    batches stop paying max_len's worth of attention FLOPs.  The
    attention mask still carries per-token validity, so the loss/metrics
    are identical to the padded-to-max_len layout."""

    def __init__(self, tokenizer: BertWordPieceTokenizer,
                 sentences: Sequence, labels: Sequence[int], *,
                 num_classes: int, batch_size: int = 32, max_len: int = 128,
                 pairs: Optional[Sequence] = None,
                 dynamic_seq_len: bool = False,
                 bucket_size: Optional[int] = None):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        if pairs is not None and len(pairs) != len(sentences):
            raise ValueError("pairs must align with sentences")
        self.tokenizer = tokenizer
        self.sentences = list(sentences)
        self.labels = list(labels)
        self.pairs = list(pairs) if pairs is not None else None
        self.num_classes = num_classes
        self._batch_size = batch_size
        self.max_len = max_len
        self.dynamic_seq_len = dynamic_seq_len
        self.bucket_size = bucket_size
        self._encoded = None         # (ids, mask, segments) cached across epochs
        self._lengths = None         # per-example real token counts

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def _encode_all(self):
        """Tokenize ONCE: sentences/tokenizer/max_len are fixed at
        construction, so later epochs slice cached arrays instead of
        re-running host-side WordPiece."""
        if self._encoded is None:
            n = len(self.sentences)
            ids = np.zeros((n, self.max_len), np.float32)
            mask = np.zeros((n, self.max_len), np.float32)
            segs = np.zeros((n, self.max_len), np.int32)
            for j in range(n):
                pair = self.pairs[j] if self.pairs else None
                i, m, sg = self.tokenizer.encode(
                    self.sentences[j], pair, max_len=self.max_len
                )
                ids[j], mask[j], segs[j] = i, m, sg
            self._encoded = (ids, mask, segs)
            self._lengths = mask.sum(axis=1).astype(np.int64)
        return self._encoded

    def segment_ids(self):
        """(N, max_len) int32 token-type ids aligned with iteration order.
        NOTE: the DSL's Embedding layer has no token-type channel yet, so
        pair inputs train on the [SEP]-delimited sequence alone; consume
        these ids from a custom layer/graph input if segments matter."""
        return self._encode_all()[2]

    def _bucket_plan(self) -> list[tuple[int, list[int]]]:
        """(bucket_len, example indices) groups, shortest bucket first.
        Bucket lengths are multiples of the quantum capped at max_len, so
        distinct feature shapes number at most ceil(max_len/quantum)."""
        from deeplearning4j_tpu.runtime.flags import bucket_length

        self._encode_all()
        q = self.bucket_size
        buckets: dict[int, list[int]] = {}
        for j, ln in enumerate(self._lengths):
            L = min(self.max_len, bucket_length(int(ln), q))
            buckets.setdefault(L, []).append(j)
        return sorted(buckets.items())

    def _emit(self, idx: list[int], seq_len: int):
        all_ids, all_mask, _ = self._encoded
        bs = self._batch_size
        count = len(idx)
        ids = np.zeros((bs, seq_len), np.float32)
        mask = np.zeros((bs, seq_len), np.float32)
        y = np.zeros((bs, self.num_classes), np.float32)
        lmask = np.zeros((bs,), np.float32)
        ids[:count] = all_ids[idx, :seq_len]
        mask[:count] = all_mask[idx, :seq_len]
        for j, src in enumerate(idx):
            y[j, self.labels[src]] = 1.0
            lmask[j] = 1.0
        # static batch shape: the tail batch pads EXAMPLES too and
        # masks them out of the loss via labels_mask
        return DataSet(ids, y, features_mask=mask, labels_mask=lmask)

    def __iter__(self):
        self._encode_all()
        n = len(self.sentences)
        bs = self._batch_size
        if not self.dynamic_seq_len:
            for lo in range(0, n, bs):
                yield self._emit(list(range(lo, min(lo + bs, n))), self.max_len)
            return
        for seq_len, idx in self._bucket_plan():
            for lo in range(0, len(idx), bs):
                yield self._emit(idx[lo : lo + bs], seq_len)

    def reset(self) -> None:
        pass
