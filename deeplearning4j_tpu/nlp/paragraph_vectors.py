"""ParagraphVectors (Doc2Vec) — `org.deeplearning4j.models.paragraphvectors` role.

Reference parity: PV-DBOW (`DBOW` sequence learning algorithm — the doc
vector predicts each word in the document) and PV-DM (`DM` — doc vector +
context mean predicts the center word), labelled documents, and
`inferVector()` for unseen documents (gradient steps on a fresh doc vector
with word vectors frozen).  Shares Word2Vec's jit-compiled negative-sampling
step; doc vectors live in their own embedding matrix.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizer import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import _ns_step


class ParagraphVectors:
    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, negative_sample: int = 5,
                 epochs: int = 5, learning_rate: float = 0.025,
                 algorithm: str = "dbow", seed: int = 42,
                 batch_size: int = 2048, tokenizer_factory=None):
        if algorithm not in ("dbow", "dm"):
            raise ValueError(f"unknown algorithm {algorithm!r}")
        self.vector_size = layer_size
        self.window = window_size
        self.min_word_frequency = min_word_frequency
        self.negative = max(1, negative_sample)
        self.epochs_ = epochs
        self.lr = learning_rate
        self.algorithm = algorithm
        self.seed = seed
        self.batch_size = batch_size
        if tokenizer_factory is None:
            tokenizer_factory = DefaultTokenizerFactory()
            tokenizer_factory.set_token_pre_processor(CommonPreprocessor())
        self.tokenizer_factory = tokenizer_factory
        self.vocab: VocabCache | None = None
        self.labels: list[str] = []
        self._label_idx: dict[str, int] = {}
        self.doc_vectors: np.ndarray | None = None
        self.syn0: np.ndarray | None = None      # word vectors
        self._syn1neg: np.ndarray | None = None  # output vectors (for infer)

    def fit(self, documents: Iterable[str], labels: Sequence[str] | None = None) -> "ParagraphVectors":
        docs = [self.tokenizer_factory.create(d).get_tokens() for d in documents]
        if labels is None:
            labels = [f"DOC_{i}" for i in range(len(docs))]
        if len(labels) != len(docs):
            raise ValueError("labels/documents length mismatch")
        self.labels = list(labels)
        self._label_idx = {l: i for i, l in enumerate(self.labels)}
        self.vocab = VocabCache(self.min_word_frequency)
        for toks in docs:
            self.vocab.track(toks)
        self.vocab.finish()
        v, d = len(self.vocab), self.vector_size
        if v == 0:
            raise ValueError("empty vocabulary")
        rng = np.random.default_rng(self.seed)
        ndocs = len(docs)
        # one concatenated embedding: rows [0,v) words, [v, v+ndocs) docs.
        # NS targets are always words; "centers" may be doc ids (DBOW).
        syn0 = ((rng.random((v + ndocs, d)) - 0.5) / d).astype(np.float32)
        synout = np.zeros((v + ndocs, d), dtype=np.float32)
        enc = [
            np.array([self.vocab.index_of(t) for t in toks if t in self.vocab], dtype=np.int32)
            for toks in docs
        ]
        ns_probs = self.vocab.negative_table()
        syn0j, synoutj = jnp.asarray(syn0), jnp.asarray(synout)
        for _ in range(self.epochs_):
            centers, targets = self._pairs(enc, v, rng)
            for i in range(0, len(centers), self.batch_size):
                c = centers[i : i + self.batch_size]
                t = targets[i : i + self.batch_size]
                negs = rng.choice(v, size=(len(c), self.negative), p=ns_probs).astype(np.int32)
                syn0j, synoutj, _ = _ns_step(
                    syn0j, synoutj, jnp.asarray(c), jnp.asarray(t),
                    jnp.asarray(negs), jnp.float32(self.lr),
                )
        full = np.asarray(syn0j)
        self.syn0 = full[:v]
        self.doc_vectors = full[v:]
        self._syn1neg = np.asarray(synoutj)[:v]
        return self

    def _pairs(self, enc, v, rng):
        cs, ts = [], []
        for doc_i, words in enumerate(enc):
            if words.size == 0:
                continue
            doc_row = v + doc_i
            if self.algorithm == "dbow":
                # doc vector predicts every word
                cs.append(np.full(words.size, doc_row, np.int32))
                ts.append(words)
            else:  # dm, pairwise approximation: doc + each context word predict center
                cs.append(np.full(words.size, doc_row, np.int32))
                ts.append(words)
                n = words.size
                for off in range(1, min(self.window, n - 1) + 1):
                    idx = np.arange(n - off)
                    cs.append(words[idx])
                    ts.append(words[idx + off])
        centers = np.concatenate(cs)
        targets = np.concatenate(ts)
        perm = rng.permutation(centers.size)
        return centers[perm], targets[perm]

    # -- lookups -----------------------------------------------------------
    def get_doc_vector(self, label: str) -> np.ndarray:
        return self.doc_vectors[self._label_idx[label]]

    def similarity(self, label_a: str, label_b: str) -> float:
        a, b = self.get_doc_vector(label_a), self.get_doc_vector(label_b)
        denom = np.linalg.norm(a) * np.linalg.norm(b)
        return float(a @ b / denom) if denom else 0.0

    def nearest_labels(self, text_or_label: str, n: int = 5) -> list[str]:
        if text_or_label in self._label_idx:
            vec = self.get_doc_vector(text_or_label)
            exclude = {text_or_label}
        else:
            vec = self.infer_vector(text_or_label)
            exclude = set()
        norms = np.linalg.norm(self.doc_vectors, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = self.doc_vectors @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = [self.labels[int(i)] for i in order if self.labels[int(i)] not in exclude]
        return out[:n]

    def infer_vector(self, text: str, steps: int = 50, lr: float = 0.05,
                     seed: int = 0) -> np.ndarray:
        """Gradient steps on a fresh doc vector with word/output vectors
        frozen (reference `inferVector`)."""
        toks = self.tokenizer_factory.create(text).get_tokens()
        words = np.array(
            [self.vocab.index_of(t) for t in toks if t in self.vocab], dtype=np.int32
        )
        rng = np.random.default_rng(seed)
        d = self.vector_size
        vec = ((rng.random(d) - 0.5) / d).astype(np.float32)
        if words.size == 0:
            return vec
        ns_probs = self.vocab.negative_table()
        u_pos = self._syn1neg[words]  # (T,D)
        for _ in range(steps):
            negs = rng.choice(len(self.vocab), size=(words.size, self.negative), p=ns_probs)
            u_neg = self._syn1neg[negs]  # (T,K,D)
            logits_p = u_pos @ vec
            logits_n = np.einsum("tkd,d->tk", u_neg, vec)
            gp = 1 / (1 + np.exp(-logits_p)) - 1.0
            gn = 1 / (1 + np.exp(-logits_n))
            grad = gp @ u_pos + np.einsum("tk,tkd->d", gn, u_neg)
            vec -= lr * grad / words.size
        return vec
