"""GloVe — `org.deeplearning4j.models.glove.Glove` role.

Reference parity: co-occurrence counting with a decaying window, then the
GloVe weighted least-squares objective with per-parameter AdaGrad.
TPU-native mechanism: co-occurrence triples (i, j, X_ij) are batched and
each AdaGrad step over a triple minibatch is one jit-compiled XLA
computation (gathers + scatter-adds), replacing the reference's per-pair
Java loop.
"""

from __future__ import annotations

import functools
from collections import defaultdict
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizer import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wc, b, bc, gw, gwc, gb, gbc, ii, jj, logx, weight, lr):
    """AdaGrad step on the GloVe objective for a batch of triples.
    w/wc: (V,D) word/context vectors; b/bc: (V,) biases; g*: AdaGrad
    accumulators; ii,jj: (B,) indices; logx: (B,) log co-occurrence;
    weight: (B,) f(X_ij)."""
    vi = w[ii]
    vj = wc[jj]
    diff = jnp.einsum("bd,bd->b", vi, vj) + b[ii] + bc[jj] - logx
    fdiff = weight * diff                       # (B,)
    grad_vi = fdiff[:, None] * vj
    grad_vj = fdiff[:, None] * vi
    # AdaGrad accumulate then scale
    gw = gw.at[ii].add(grad_vi**2)
    gwc = gwc.at[jj].add(grad_vj**2)
    gb = gb.at[ii].add(fdiff**2)
    gbc = gbc.at[jj].add(fdiff**2)
    w = w.at[ii].add(-lr * grad_vi * jax.lax.rsqrt(gw[ii] + 1e-8))
    wc = wc.at[jj].add(-lr * grad_vj * jax.lax.rsqrt(gwc[jj] + 1e-8))
    b = b.at[ii].add(-lr * fdiff * jax.lax.rsqrt(gb[ii] + 1e-8))
    bc = bc.at[jj].add(-lr * fdiff * jax.lax.rsqrt(gbc[jj] + 1e-8))
    loss = 0.5 * jnp.mean(weight * diff**2)
    return w, wc, b, bc, gw, gwc, gb, gbc, loss


class Glove:
    def __init__(self, layer_size: int = 100, window_size: int = 10,
                 min_word_frequency: int = 1, learning_rate: float = 0.05,
                 epochs: int = 25, x_max: float = 100.0, alpha: float = 0.75,
                 batch_size: int = 4096, seed: int = 42, tokenizer_factory=None):
        self.vector_size = layer_size
        self.window = window_size
        self.min_word_frequency = min_word_frequency
        self.lr = learning_rate
        self.epochs_ = epochs
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        if tokenizer_factory is None:
            tokenizer_factory = DefaultTokenizerFactory()
            tokenizer_factory.set_token_pre_processor(CommonPreprocessor())
        self.tokenizer_factory = tokenizer_factory
        self.vocab: VocabCache | None = None
        self.syn0: np.ndarray | None = None

    def fit(self, sentences: Iterable[str]) -> "Glove":
        corpus = [self.tokenizer_factory.create(s).get_tokens() for s in sentences]
        self.vocab = VocabCache(self.min_word_frequency)
        for toks in corpus:
            self.vocab.track(toks)
        self.vocab.finish()
        v = len(self.vocab)
        if v == 0:
            raise ValueError("empty vocabulary")
        # co-occurrence with 1/distance weighting (standard GloVe)
        cooc: dict[tuple[int, int], float] = defaultdict(float)
        for toks in corpus:
            idx = [self.vocab.index_of(t) for t in toks if t in self.vocab]
            for c, wi in enumerate(idx):
                for off in range(1, min(self.window, len(idx) - c - 1) + 1):
                    wj = idx[c + off]
                    cooc[(wi, wj)] += 1.0 / off
                    cooc[(wj, wi)] += 1.0 / off
        if not cooc:
            raise ValueError("no co-occurrences found")
        triples = np.array([(i, j, x) for (i, j), x in cooc.items()], dtype=np.float64)
        ii_all = triples[:, 0].astype(np.int32)
        jj_all = triples[:, 1].astype(np.int32)
        x_all = triples[:, 2]
        logx_all = np.log(x_all).astype(np.float32)
        weight_all = np.minimum(1.0, (x_all / self.x_max) ** self.alpha).astype(np.float32)

        rng = np.random.default_rng(self.seed)
        d = self.vector_size
        init = lambda shape: ((rng.random(shape) - 0.5) / d).astype(np.float32)
        state = [
            jnp.asarray(init((v, d))), jnp.asarray(init((v, d))),
            jnp.zeros(v, jnp.float32), jnp.zeros(v, jnp.float32),
            jnp.ones((v, d), jnp.float32) * 1e-8, jnp.ones((v, d), jnp.float32) * 1e-8,
            jnp.ones(v, jnp.float32) * 1e-8, jnp.ones(v, jnp.float32) * 1e-8,
        ]
        n = ii_all.size
        bs = min(self.batch_size, n)
        for _ in range(self.epochs_):
            perm = rng.permutation(n)
            # wrap-pad to a batch multiple -> single compiled executable
            usable = (n // bs) * bs if n >= bs else n
            perm = perm[:usable] if usable else perm
            for i in range(0, len(perm), bs):
                sl = perm[i : i + bs]
                *state, _ = _glove_step(
                    *state,
                    jnp.asarray(ii_all[sl]), jnp.asarray(jj_all[sl]),
                    jnp.asarray(logx_all[sl]), jnp.asarray(weight_all[sl]),
                    jnp.float32(self.lr),
                )
        self.syn0 = np.asarray(state[0]) + np.asarray(state[1])  # w + wc (standard)
        return self

    # -- lookups -----------------------------------------------------------
    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def get_word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def words_nearest(self, word: str, n: int = 10) -> list[str]:
        vec = self.get_word_vector(word)
        norms = np.linalg.norm(self.syn0, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = self.syn0 @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        return [self.vocab.word_for(int(i)) for i in order if self.vocab.word_for(int(i)) != word][:n]
