"""NLP subsystem — the `deeplearning4j-nlp` role (Word2Vec, GloVe,
ParagraphVectors, tokenizers, vocab, word-vector serialization)."""

from deeplearning4j_tpu.nlp.tokenizer import (
    CommonPreprocessor,
    DefaultTokenizer,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
from deeplearning4j_tpu.nlp.wordpiece import (
    BasicTokenizer,
    BertIterator,
    BertWordPieceTokenizer,
)

__all__ = [
    "BasicTokenizer",
    "BertIterator",
    "BertWordPieceTokenizer",
    "DefaultTokenizer",
    "DefaultTokenizerFactory",
    "NGramTokenizerFactory",
    "CommonPreprocessor",
    "VocabCache",
    "VocabWord",
    "Word2Vec",
    "Glove",
    "ParagraphVectors",
    "WordVectorSerializer",
]
