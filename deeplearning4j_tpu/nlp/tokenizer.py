"""Tokenizers — `org.deeplearning4j.text.tokenization` role.

Reference parity: `DefaultTokenizer` (whitespace/punct split),
`NGramTokenizerFactory`, `CommonPreprocessor` (lowercase + strip
punctuation), and the `TokenizerFactory` SPI that pipelines a token
preprocessor into every produced tokenizer.
"""

from __future__ import annotations

import re
from typing import Callable, Iterator, Optional

_TOKEN_RE = re.compile(r"\S+")
_PUNCT_RE = re.compile(r"[^\w]", re.UNICODE)


class CommonPreprocessor:
    """Lowercase + strip punctuation (`CommonPreprocessor` role)."""

    def pre_process(self, token: str) -> str:
        return _PUNCT_RE.sub("", token.lower())

    __call__ = pre_process


class DefaultTokenizer:
    def __init__(self, text: str, preprocessor: Optional[Callable[[str], str]] = None):
        self._tokens = _TOKEN_RE.findall(text)
        self._pre = preprocessor

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> list[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre(t)
            if t:
                out.append(t)
        return out

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class DefaultTokenizerFactory:
    """`TokenizerFactory` SPI: create() per document, with a shared token
    preprocessor."""

    def __init__(self):
        self._pre: Optional[Callable[[str], str]] = None

    def set_token_pre_processor(self, pre: Callable[[str], str]) -> None:
        self._pre = pre

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory:
    """Emits word n-grams joined by spaces (`NGramTokenizerFactory` role)."""

    def __init__(self, min_n: int, max_n: int):
        self.min_n, self.max_n = min_n, max_n
        self._pre: Optional[Callable[[str], str]] = None

    def set_token_pre_processor(self, pre: Callable[[str], str]) -> None:
        self._pre = pre

    def create(self, text: str):
        base = DefaultTokenizer(text, self._pre).get_tokens()
        grams: list[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                grams.append(" ".join(base[i : i + n]))

        class _T:
            def get_tokens(self):
                return grams

            def count_tokens(self):
                return len(grams)

            def __iter__(self):
                return iter(grams)

        return _T()
