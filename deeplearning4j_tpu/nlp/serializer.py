"""Word-vector serialization — `WordVectorSerializer` role.

Reference parity: the word2vec text format ("V D" header, then one
"word v1 v2 ..." line per word) readable by the original C tool, gensim and
the reference's `WordVectorSerializer.writeWord2VecModel/readWord2VecModel`.
"""

from __future__ import annotations

import gzip

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class _StaticWordVectors:
    """Lookup-only word vectors loaded from disk."""

    def __init__(self, words: list[str], matrix: np.ndarray):
        self.syn0 = matrix
        self.vocab = VocabCache()
        for w in words:
            self.vocab.track([w])
        # preserve file order as index order (VocabCache orders by count,
        # all equal here -> insertion order of most_common is preserved)
        self.vocab.finish()
        self._order = {w: i for i, w in enumerate(words)}

    def has_word(self, word: str) -> bool:
        return word in self._order

    def get_word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self._order[word]]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def words_nearest(self, word: str, n: int = 10) -> list[str]:
        vec = self.get_word_vector(word)
        norms = np.linalg.norm(self.syn0, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = self.syn0 @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        words = list(self._order)
        return [words[int(i)] for i in order if words[int(i)] != word][:n]

    def vocab_words(self) -> list[str]:
        return list(self._order)


class WordVectorSerializer:
    @staticmethod
    def write_word2vec_model(model, path: str) -> None:
        """word2vec text format; .gz suffix compresses."""
        words = model.vocab_words() if hasattr(model, "vocab_words") else model.vocab.words()
        mat = model.syn0
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt", encoding="utf-8") as f:
            f.write(f"{len(words)} {mat.shape[1]}\n")
            for i, w in enumerate(words):
                vec = " ".join(f"{x:.6f}" for x in mat[i])
                f.write(f"{w} {vec}\n")

    @staticmethod
    def read_word2vec_model(path: str) -> _StaticWordVectors:
        opener = gzip.open if path.endswith(".gz") else open
        words: list[str] = []
        rows: list[np.ndarray] = []
        with opener(path, "rt", encoding="utf-8") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                words.append(parts[0])
                rows.append(np.array(parts[1 : d + 1], dtype=np.float32))
        if len(words) != v:
            raise ValueError(f"header declared {v} words, file had {len(words)}")
        return _StaticWordVectors(words, np.stack(rows))
