"""Word2Vec — `org.deeplearning4j.models.word2vec.Word2Vec` role.

Reference parity: CBOW + SkipGram with negative sampling and hierarchical
softmax, window/min-frequency/subsampling/learning-rate knobs, a fluent
Builder, `wordsNearest`/`similarity`/`getWordVectorMatrix` lookups.

TPU-native mechanism: where the reference trains word-at-a-time with
Hogwild threads over libnd4j kernels (SkipGram/CBOW declarable ops), here
pair generation is vectorized on host (numpy) and the SGD step over a
minibatch of (center, context, negatives) triples is ONE jit-compiled XLA
computation — embedding gathers + batched dot products on the MXU, scatter-
add updates via segment_sum.  Negative sampling shares the step; HS uses the
padded Huffman-matrix layout from VocabCache (gather + masked sigmoid, no
tree walk).
"""

from __future__ import annotations

import functools
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizer import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.runtime.mesh import shard_map


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _ns_step(syn0, syn1neg, center, pos, negs, lr):
    """One negative-sampling SGD step over a batch of pairs.
    syn0: (V,D) input vectors; syn1neg: (V,D) output vectors;
    center,pos: (B,) int32; negs: (B,K) int32."""
    v = syn0[center]                       # (B,D)
    targets = jnp.concatenate([pos[:, None], negs], axis=1)  # (B,1+K)
    labels = jnp.concatenate(
        [jnp.ones((pos.shape[0], 1)), jnp.zeros(negs.shape)], axis=1
    )                                       # (B,1+K)
    u = syn1neg[targets]                    # (B,1+K,D)
    logits = jnp.einsum("bd,bkd->bk", v, u)
    g = (jax.nn.sigmoid(logits) - labels)   # (B,1+K)
    grad_v = jnp.einsum("bk,bkd->bd", g, u)
    grad_u = g[..., None] * v[:, None, :]   # (B,1+K,D)
    syn0 = syn0.at[center].add(-lr * grad_v)
    syn1neg = syn1neg.at[targets.reshape(-1)].add(
        -lr * grad_u.reshape(-1, grad_u.shape[-1])
    )
    loss = jnp.mean(
        jnp.log1p(jnp.exp(-jnp.where(labels > 0, logits, -logits)))
    )
    return syn0, syn1neg, loss


def _make_ns_step_dp(mesh):
    """Data-parallel negative-sampling step — the role of the reference's
    distributed Word2Vec (SparkWord2Vec trains word vectors through the
    parameter server; SURVEY.md §2.2 "NLP").  TPU-native version: pair
    batches shard over the mesh's data axis, each shard computes its
    scatter-add delta against the replicated tables, deltas AllReduce via
    psum — exact synchronous SGD, no server."""
    from jax.sharding import PartitionSpec as P

    def body(syn0, syn1neg, center, pos, negs, lr):
        v = syn0[center]
        targets = jnp.concatenate([pos[:, None], negs], axis=1)
        labels = jnp.concatenate(
            [jnp.ones((pos.shape[0], 1)), jnp.zeros(negs.shape)], axis=1
        )
        u = syn1neg[targets]
        logits = jnp.einsum("bd,bkd->bk", v, u)
        g = (jax.nn.sigmoid(logits) - labels)
        grad_v = jnp.einsum("bk,bkd->bd", g, u)
        grad_u = g[..., None] * v[:, None, :]
        d0 = jnp.zeros_like(syn0).at[center].add(-lr * grad_v)
        d1 = jnp.zeros_like(syn1neg).at[targets.reshape(-1)].add(
            -lr * grad_u.reshape(-1, grad_u.shape[-1])
        )
        d0 = jax.lax.psum(d0, "data")
        d1 = jax.lax.psum(d1, "data")
        loss = jnp.mean(
            jnp.log1p(jnp.exp(-jnp.where(labels > 0, logits, -logits)))
        )
        return syn0 + d0, syn1neg + d1, jax.lax.pmean(loss, "data")

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), P("data"), P("data"), P("data"), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _hs_step(syn0, syn1, center, codes, points, mask, lr):
    """Hierarchical-softmax SGD step: codes/points/mask are the padded
    Huffman rows for each TARGET word; center indexes syn0."""
    v = syn0[center]                        # (B,D)
    u = syn1[points]                        # (B,L,D)
    logits = jnp.einsum("bd,bld->bl", v, u)
    g = (jax.nn.sigmoid(logits) - (1.0 - codes)) * mask
    grad_v = jnp.einsum("bl,bld->bd", g, u)
    grad_u = g[..., None] * v[:, None, :]
    syn0 = syn0.at[center].add(-lr * grad_v)
    syn1 = syn1.at[points.reshape(-1)].add(-lr * grad_u.reshape(-1, grad_u.shape[-1]))
    per = jnp.log1p(jnp.exp(-jnp.where(codes < 0.5, logits, -logits))) * mask
    loss = jnp.sum(per) / jnp.maximum(jnp.sum(mask), 1.0)
    return syn0, syn1, loss


class Word2Vec:
    """Use via the Builder:

        w2v = (Word2Vec.builder()
               .min_word_frequency(5).layer_size(100).window_size(5)
               .elements_learning_algorithm("skipgram")  # or "cbow"
               .negative_sample(5)                       # 0 -> hierarchical softmax
               .epochs(1).seed(42).build())
        w2v.fit(sentences)          # iterable of strings
    """

    def __init__(self, **kw):
        self.vector_size = kw.get("layer_size", 100)
        self.window = kw.get("window_size", 5)
        self.min_word_frequency = kw.get("min_word_frequency", 5)
        self.negative = kw.get("negative_sample", 5)
        self.algorithm = kw.get("algorithm", "skipgram")
        self.epochs_ = kw.get("epochs", 1)
        self.lr = kw.get("learning_rate", 0.025)
        self.min_lr = kw.get("min_learning_rate", 1e-4)
        self.subsample = kw.get("sampling", 1e-3)
        self.seed = kw.get("seed", 42)
        self.batch_size = kw.get("batch_size", 2048)
        # >1: shard pair batches over that many devices (the reference's
        # SparkWord2Vec/workers role, realized as synchronous SPMD)
        self.workers_ = kw.get("workers", 1)
        self.tokenizer_factory = kw.get("tokenizer_factory") or self._default_tf()
        self.vocab: VocabCache | None = None
        self.syn0: np.ndarray | None = None

    @staticmethod
    def _default_tf():
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        return tf

    # -- builder -----------------------------------------------------------
    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            key_map = {
                "min_word_frequency": "min_word_frequency",
                "layer_size": "layer_size",
                "window_size": "window_size",
                "negative_sample": "negative_sample",
                "epochs": "epochs",
                "learning_rate": "learning_rate",
                "min_learning_rate": "min_learning_rate",
                "sampling": "sampling",
                "seed": "seed",
                "batch_size": "batch_size",
                "tokenizer_factory": "tokenizer_factory",
                "workers": "workers",
            }
            if name in key_map:
                def setter(v):
                    self._kw[key_map[name]] = v
                    return self
                return setter
            raise AttributeError(name)

        def elements_learning_algorithm(self, alg: str):
            alg = alg.lower()
            if alg not in ("skipgram", "cbow"):
                raise ValueError(f"unknown algorithm {alg!r}")
            self._kw["algorithm"] = alg
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    # -- training ----------------------------------------------------------
    def _tokenize_corpus(self, sentences: Iterable[str]) -> list[list[str]]:
        return [self.tokenizer_factory.create(s).get_tokens() for s in sentences]

    def fit(self, sentences: Iterable[str]) -> "Word2Vec":
        corpus = self._tokenize_corpus(sentences)
        self.vocab = VocabCache(self.min_word_frequency)
        for toks in corpus:
            self.vocab.track(toks)
        self.vocab.finish()
        v = len(self.vocab)
        if v == 0:
            raise ValueError("empty vocabulary after min-frequency filtering")
        rng = np.random.default_rng(self.seed)
        d = self.vector_size
        syn0 = ((rng.random((v, d)) - 0.5) / d).astype(np.float32)
        syn_out = np.zeros((v, d), dtype=np.float32)
        # index-encode corpus once
        enc = [
            np.array([self.vocab.index_of(t) for t in toks if t in self.vocab],
                     dtype=np.int32)
            for toks in corpus
        ]
        enc = [e for e in enc if e.size > 1]
        keep = self.vocab.subsample_keep_probs(self.subsample) if self.subsample else None
        ns_probs = self.vocab.negative_table()
        use_hs = self.negative == 0
        if use_hs:
            codes_m, points_m, mask_m = self.vocab.huffman_matrices()
        ns_step = _ns_step
        if self.workers_ > 1 and not use_hs:
            devs = jax.devices()
            if len(devs) < self.workers_:
                raise ValueError(
                    f"workers={self.workers_} but only {len(devs)} devices "
                    "visible; distributed Word2Vec shards pair batches over "
                    "devices"
                )
            if self.batch_size % self.workers_:
                raise ValueError(
                    f"batch_size {self.batch_size} must divide evenly over "
                    f"workers={self.workers_}"
                )
            from jax.sharding import Mesh

            ns_step = _make_ns_step_dp(
                Mesh(np.array(devs[: self.workers_]), ("data",))
            )
        elif self.workers_ > 1:
            raise ValueError(
                "distributed Word2Vec requires negative sampling "
                "(negative_sample > 0); hierarchical softmax stays "
                "single-device"
            )
        total_steps = 0
        planned = max(1, self.epochs_ * sum(len(e) for e in enc))
        seen = 0
        syn0j, syn_outj = jnp.asarray(syn0), jnp.asarray(syn_out)
        for _ in range(self.epochs_):
            centers, contexts = self._generate_pairs(enc, keep, rng)
            # pad to a batch multiple (wrap-around) so every step hits the
            # same compiled executable — ragged final batches would
            # recompile, and the workers>1 shard_map step needs a
            # devices-divisible batch.  A corpus SMALLER than batch_size
            # shrinks the batch instead of tiling pairs up to batch_size
            # (tiling would multiply every pair's gradient, inflating the
            # effective learning rate ~batch/len times).
            bs = self.batch_size
            if len(centers) < bs:
                bs = max(
                    self.workers_,
                    len(centers) - len(centers) % self.workers_,
                )
            if len(centers) % bs:
                n = len(centers) + bs - len(centers) % bs
                centers = np.resize(centers, n)
                contexts = np.resize(contexts, n)
            for i in range(0, len(centers), bs):
                c = centers[i : i + bs]
                o = contexts[i : i + bs]
                # lr decays linearly with progress; passed as a traced scalar
                # so every step reuses ONE compiled executable
                lr = jnp.float32(max(self.min_lr, self.lr * (1.0 - seen / planned)))
                if use_hs:
                    syn0j, syn_outj, _ = _hs_step(
                        syn0j, syn_outj, jnp.asarray(c),
                        jnp.asarray(codes_m[o]), jnp.asarray(points_m[o]),
                        jnp.asarray(mask_m[o]), lr,
                    )
                else:
                    negs = rng.choice(v, size=(len(c), self.negative), p=ns_probs).astype(np.int32)
                    syn0j, syn_outj, _ = ns_step(
                        syn0j, syn_outj, jnp.asarray(c), jnp.asarray(o),
                        jnp.asarray(negs), lr,
                    )
                total_steps += 1
                seen += len(c)
        self.syn0 = np.asarray(syn0j)
        del syn_outj
        return self

    def _generate_pairs(self, enc, keep, rng):
        """Vectorized (center, context) pair generation with dynamic window
        (word2vec samples an effective window b ~ U[1, window])."""
        all_c, all_o = [], []
        for sent in enc:
            if keep is not None:
                m = rng.random(sent.size) < keep[sent]
                sent = sent[m]
            n = sent.size
            if n < 2:
                continue
            b = rng.integers(1, self.window + 1, size=n)
            for off in range(1, self.window + 1):
                # pairs (i, i+off) both directions where off <= effective window
                idx = np.arange(n - off)
                ok = (b[idx] >= off) | (b[idx + off] >= off)
                i1, i2 = sent[idx[ok]], sent[idx[ok] + off]
                if self.algorithm == "skipgram":
                    all_c.extend([i1, i2])
                    all_o.extend([i2, i1])
                else:  # cbow approximated pairwise (context predicts center)
                    all_c.extend([i2, i1])
                    all_o.extend([i1, i2])
        if not all_c:
            raise ValueError("no training pairs generated")
        centers = np.concatenate(all_c)
        contexts = np.concatenate(all_o)
        perm = rng.permutation(centers.size)
        return centers[perm].astype(np.int32), contexts[perm].astype(np.int32)

    # -- lookups (WordVectors interface role) ------------------------------
    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def get_word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def words_nearest(self, word_or_vec, n: int = 10) -> list[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        norms = np.linalg.norm(self.syn0, axis=1) * max(np.linalg.norm(vec), 1e-12)
        sims = self.syn0 @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_for(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) == n:
                break
        return out

    def vocab_words(self) -> list[str]:
        return self.vocab.words() if self.vocab else []
