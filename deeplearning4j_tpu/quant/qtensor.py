"""QuantizedTensor — the `(int8 q, f32 scale)` weight pair as a pytree node.

Registering the pair as a pytree node (with keys, so path-flattened
views name the children ``...W.q`` / ``...W.scale``) is what makes the
quantized tree flow through the whole stack unchanged: jit flattens it
into plain int8/f32 leaves, hot-swap verification checks those leaves'
shape/dtype/finiteness individually (finiteness already skips integer
dtypes), checkpoints save/load them positionally, and `tree_bytes` /
`param_count` just work.

Dequantization is ``q.astype(dtype) * scale`` with the scale broadcast
over the LAST axis — the output-channel axis for every supported weight
layout ((n_in, n_out) dense/embedding, HWIO/…IO conv kernels).
``astype`` aliases `dequant`, so any layer that still runs the classic
``params["W"].astype(x.dtype)`` idiom transparently gets the
dequantized f32 weights (correct, if unfused) instead of crashing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class QuantizedTensor:
    """One quantized weight: int8 values + per-output-channel f32 scales.

    ``q``: int8 array of the original weight's shape; ``scale``: f32 of
    shape ``(q.shape[-1],)``.  Dequantized value ≈ ``q * scale``.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    # -- array-ish surface -------------------------------------------------
    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def dtype(self):
        """Storage dtype (int8) — what the tree's weight leaf holds."""
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return int(getattr(self.q, "nbytes", 0)) + int(
            getattr(self.scale, "nbytes", 0)
        )

    def dequant(self, dtype=jnp.float32):
        """The dense weights this pair stands for (f32 accumulate path:
        cast THEN scale, both in the target dtype)."""
        return self.q.astype(dtype) * self.scale.astype(dtype)

    # legacy layer idiom `params["W"].astype(x.dtype)` keeps working —
    # it just pays the unfused dequantize-then-use cost
    astype = dequant

    def __repr__(self) -> str:
        return (f"QuantizedTensor(shape={tuple(self.shape)}, "
                f"scale_shape={tuple(np.shape(self.scale))})")


def _flatten_with_keys(t: QuantizedTensor):
    return (
        ((jax.tree_util.GetAttrKey("q"), t.q),
         (jax.tree_util.GetAttrKey("scale"), t.scale)),
        None,
    )


def _flatten(t: QuantizedTensor):
    return (t.q, t.scale), None


def _unflatten(aux, children) -> QuantizedTensor:
    q, scale = children
    return QuantizedTensor(q, scale)


jax.tree_util.register_pytree_with_keys(
    QuantizedTensor, _flatten_with_keys, _unflatten, _flatten,
)


def quantize_array(w, *, bits: int = 8) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of one weight.

    The channel axis is the LAST axis (n_out for dense/embedding, O for
    HWIO conv kernels); the scale is ``max|w|/127`` per channel and
    values round to ``[-127, 127]`` (the symmetric range — -128 is never
    used, so q and -q are both representable).  All-zero channels get
    scale 1.0 so dequantization stays exact.  Host-side numpy on
    purpose: PTQ is an offline transform, not a traced op.
    """
    if bits != 8:
        raise ValueError(f"only int8 supported (got bits={bits})")
    a = np.asarray(w, dtype=np.float32)
    if a.ndim < 1:
        raise ValueError("cannot channel-quantize a scalar")
    qmax = 127.0
    amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)))
    scale = amax / qmax
    scale = np.where(scale > 0.0, scale, 1.0).astype(np.float32)
    q = np.clip(np.round(a / scale), -qmax, qmax).astype(np.int8)
    return QuantizedTensor(jnp.asarray(q), jnp.asarray(scale))
