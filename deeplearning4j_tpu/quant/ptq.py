"""Post-training quantization: `quantize(model)` -> servable int8 model.

Covers every matmul/conv/embedding weight of the layers that exist
today — Dense/OutputLayer (and the zoo/modelimport models built from
them), Conv1D/2D/3D, SeparableConv2D (both kernels), Deconv2D and
Embedding — with symmetric per-output-channel scales (`qtensor.
quantize_array`).  Biases, norm parameters, recurrent gates and
attention projections stay f32: they are a rounding error of the
weight bytes and the risky numerics.  The quantized layer set is
derived from the CONFIG (layer types by name), so the same walk
rebuilds an identical tree STRUCTURE at checkpoint-restore time
(`requantize_structure` — values then stream in from the file).

The transform is inference-only: the optimizer state is dropped (an
int8 tree cannot take gradient updates) and `model._quantized` carries
the scheme marker that keys the cost registry's distinct programs
(``Model._step_key_suffix``), the checkpoint meta, and the serving
status surface.
"""

from __future__ import annotations

import logging

import jax

from deeplearning4j_tpu.quant.qtensor import QuantizedTensor, quantize_array

log = logging.getLogger("deeplearning4j_tpu")

SCHEME = "int8-perchannel-symmetric/1"


def _quantizable_types():
    """(layer types -> quantized-param spec), resolved lazily — the
    layer modules import quant.functional, so a module-level table here
    would be a circular import (the PR 8 observe/health lesson).

    A spec is ``{group: names}``: ``""`` names params at the layer's
    top level, any other key names a NESTED param-dict group (the
    transformer block keeps its attention projections under
    ``params["attn"]``).  Plain tuples are shorthand for top-level."""
    from deeplearning4j_tpu.nn.conf import attention as A
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.nn.conf import layers_nd as LN
    from deeplearning4j_tpu.nn.conf import recurrent as R

    qkv = ("Wq", "Wk", "Wv", "Wo")
    return (
        (L.SeparableConv2D, ("depthW", "pointW")),
        (L.Conv2D, ("W",)),
        (L.Deconv2D, ("W",)),
        (L.Dense, ("W",)),             # OutputLayer subclasses Dense
        (L.Embedding, ("W",)),
        (L.ChunkedSoftmaxOutputLayer, ("W",)),
        (LN.Conv1D, ("W",)),
        (LN.Conv3D, ("W",)),
        (R.RnnOutputLayer, ("W",)),
        (A.SelfAttentionLayer, qkv),
        (A.TransformerEncoderBlock,
         {"": ("W1", "W2"), "attn": qkv}),
    )


def _layer_configs(conf) -> dict:
    """name -> layer config, for Sequential and Graph configurations."""
    layers = getattr(conf, "layers", None)
    if layers is not None:
        return {l.name: l for l in layers}
    nodes = getattr(conf, "nodes", None)
    if nodes is not None:
        return {n.name: n.layer for n in nodes if n.layer is not None}
    return {}


def _quant_spec(layer) -> dict:
    for cls, spec in _quantizable_types():
        if isinstance(layer, cls):
            return spec if isinstance(spec, dict) else {"": spec}
    return {}


def _quantize_group(group: dict, names, *, min_elements: int) -> dict:
    new = {}
    for pname, arr in group.items():
        if (pname in names and getattr(arr, "ndim", 0) >= 2
                and arr.size >= min_elements
                and not isinstance(arr, QuantizedTensor)):
            new[pname] = quantize_array(arr)
        else:
            new[pname] = arr
    return new


def quantize_params(conf, params, *, min_elements: int = 0) -> dict:
    """The params tree with every quantizable weight replaced by a
    `QuantizedTensor`; everything else is carried by reference."""
    configs = _layer_configs(conf)
    out = {}
    for lname, lp in params.items():
        layer = configs.get(lname)
        spec = _quant_spec(layer) if layer is not None else {}
        if not spec or not isinstance(lp, dict):
            out[lname] = lp
            continue
        new = dict(lp)
        for group, names in spec.items():
            if group == "":
                new.update(_quantize_group(
                    lp, names, min_elements=min_elements
                ))
            elif isinstance(lp.get(group), dict):
                new[group] = _quantize_group(
                    lp[group], names, min_elements=min_elements
                )
        out[lname] = new
    return out


def quantize(model, *, min_elements: int = 0, copy: bool = True):
    """Int8-quantize a built model's weights for serving.

    ``copy=True`` (default) returns a NEW model over the same config —
    the f32 original keeps training/serving untouched.  ``copy=False``
    converts in place (the checkpoint-restore path, where the f32 tree
    is about to be discarded anyway).  Either way the result's step-fn
    cache is empty, so its infer programs rebuild against the int8 tree
    and register with the cost registry under int8-marked keys.
    """
    if model.params is None:
        model.init()
    qparams = quantize_params(model.conf, model.params,
                              min_elements=min_elements)
    if copy:
        target = type(model)(model.conf)
        target.net_state = model.net_state
        target.iteration = model.iteration
        target.epoch = model.epoch
        for attr in ("_serialize_class_name",):
            if hasattr(model, attr):
                setattr(target, attr, getattr(model, attr))
    else:
        target = model
        target.opt_state = None            # int8 weights take no updates
        target._step_fns.clear()           # f32-shaped programs are stale
        if getattr(target, "_infer_fn", None) is not None:
            target._infer_fn = None        # GraphModel's cached program
    target.params = qparams
    target._quantized = {"scheme": SCHEME, "min_elements": min_elements}
    _gauge_bytes(qparams)
    log.info("quantized %d weight tensor(s) (%s)",
             sum(1 for _ in _iter_quantized(qparams)), SCHEME)
    return target


def requantize_structure(model, meta: dict | None = None):
    """Rebuild the quantized tree STRUCTURE on a freshly-initialized
    model (checkpoint restore: structure comes from code, data from the
    file).  The scales computed here are placeholders — `_load_npz_into`
    overwrites every leaf positionally right after.  `meta` is the
    checkpoint's recorded quantization config: the walk must re-run with
    the SAME knobs (a different min_elements changes the leaf count and
    the positional load would mis-align), and an unknown scheme is a
    hard error, not a silent guess."""
    meta = meta or {}
    scheme = meta.get("scheme", SCHEME)
    if scheme != SCHEME:
        raise ValueError(
            f"checkpoint quantization scheme {scheme!r} is not supported "
            f"by this build (expected {SCHEME!r})"
        )
    return quantize(
        model, copy=False,
        min_elements=int(meta.get("min_elements", 0)),
    )


def _iter_quantized(params):
    stack = [params]
    while stack:
        node = stack.pop()
        if isinstance(node, QuantizedTensor):
            yield node
        elif isinstance(node, dict):
            stack.extend(node.values())


def is_quantized(model) -> bool:
    return getattr(model, "_quantized", None) is not None


def dequantize_tree(params):
    """The f32 tree a quantized params tree stands for (debug/parity
    tooling — serving never materializes this)."""
    def deq(leaf):
        return leaf.dequant() if isinstance(leaf, QuantizedTensor) else leaf

    return jax.tree.map(
        deq, params, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def quantized_bytes(params) -> dict:
    """Byte accounting of a (possibly) quantized tree: actual bytes,
    the f32-equivalent bytes of the quantized weights, and the ratio —
    also pushed to the ``dl4jtpu_quant_params_bytes`` gauge."""
    import numpy as np

    total = 0
    quantized = 0
    f32_equiv = 0
    for leaf in jax.tree.leaves(params):
        total += int(getattr(leaf, "nbytes", 0))
    for qt in _iter_quantized(params):
        quantized += qt.nbytes
        f32_equiv += int(np.prod(qt.shape)) * 4
    return {
        "tree_bytes": total,
        "quantized_bytes": quantized,
        "f32_equiv_bytes": f32_equiv,
        "ratio": (quantized / f32_equiv) if f32_equiv else None,
    }


def _macro_f1(y_true, y_pred, n_classes: int) -> float:
    import numpy as np

    f1s = []
    for c in range(n_classes):
        tp = int(np.sum((y_pred == c) & (y_true == c)))
        fp = int(np.sum((y_pred == c) & (y_true != c)))
        fn = int(np.sum((y_pred != c) & (y_true == c)))
        denom = 2 * tp + fp + fn
        f1s.append((2 * tp / denom) if denom else 1.0)
    return float(np.mean(f1s))


def parity_check(reference, quantized, features, labels=None, *,
                 top1_tol: float = 0.01, f1_tol: float = 0.02) -> dict:
    """The evaluation-parity gate quantized serving ships behind.

    Runs both models' `output()` on `features` and compares argmax
    predictions: without `labels`, top-1 DISAGREEMENT between the two
    models must stay within ``top1_tol``; with integer `labels`, the
    top-1 accuracy delta (vs the labels) gates on ``top1_tol`` and the
    macro-F1 delta on ``f1_tol``.  The verdict lands on
    ``dl4jtpu_quant_parity_checks_total{result=pass|fail}`` and the
    full measurement comes back for bench rows / test asserts.
    """
    import numpy as np

    ref_out = reference.output(features)
    q_out = quantized.output(features)
    if isinstance(ref_out, tuple):          # multi-output graph: head 0
        ref_out, q_out = ref_out[0], q_out[0]
    ref_pred = np.asarray(ref_out).argmax(axis=-1).ravel()
    q_pred = np.asarray(q_out).argmax(axis=-1).ravel()
    result = {
        "n": int(ref_pred.size),
        "top1_agreement": float((ref_pred == q_pred).mean()),
    }
    result["top1_delta"] = 1.0 - result["top1_agreement"]
    ok = result["top1_delta"] <= top1_tol
    if labels is not None:
        y = np.asarray(labels).ravel().astype(np.int64)
        n_classes = int(np.asarray(ref_out).shape[-1])
        result["top1_ref"] = float((ref_pred == y).mean())
        result["top1_quant"] = float((q_pred == y).mean())
        result["top1_delta"] = abs(
            result["top1_ref"] - result["top1_quant"]
        )
        result["f1_ref"] = _macro_f1(y, ref_pred, n_classes)
        result["f1_quant"] = _macro_f1(y, q_pred, n_classes)
        result["f1_delta"] = abs(result["f1_ref"] - result["f1_quant"])
        ok = (result["top1_delta"] <= top1_tol
              and result["f1_delta"] <= f1_tol)
    result["pass"] = bool(ok)
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_quant_parity_checks_total").inc(
            result="pass" if ok else "fail"
        )
    except Exception as e:
        log.debug("quant parity metric failed: %s", e)
    return result


def _gauge_bytes(params) -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        b = quantized_bytes(params)
        g = registry().gauge("dl4jtpu_quant_params_bytes")
        g.set(b["quantized_bytes"], kind="quantized")
        g.set(b["f32_equiv_bytes"], kind="f32_equiv")
    except Exception as e:
        log.debug("quant params-bytes gauge failed: %s", e)
