"""Quantization-aware functional ops the layer applies dispatch through.

Each helper accepts EITHER a plain array weight (the f32 path — exactly
the op the layer ran before quantization existed) or a
`QuantizedTensor`, so the layer code has one call site and zero
branches on model state.  The quantized dense path routes through the
fused dequant-matmul (ops/dequant_matmul.py — kernel-selection rule in
docs/quantization.md); conv kernels dequantize-then-conv (XLA fuses the
cast into the conv's weight read); embedding lookups gather int8 ROWS
first and dequantize only what was gathered — 1/4 of the table bytes
per lookup, the channel where weight-only int8 pays even on CPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.dequant_matmul import dequant_matmul
from deeplearning4j_tpu.quant.qtensor import QuantizedTensor


def matmul(x, w):
    """``x @ w`` for a plain or quantized weight; quantized runs the
    fused dequant-matmul with f32 accumulation and returns x.dtype."""
    if isinstance(w, QuantizedTensor):
        return dequant_matmul(x, w.q, w.scale).astype(x.dtype)
    return x @ w.astype(x.dtype)


def conv_weight(w, dtype):
    """Dense kernel for a conv: dequantized (cast folded into the conv)
    for a QuantizedTensor, the usual dtype cast otherwise."""
    if isinstance(w, QuantizedTensor):
        return w.dequant(dtype)
    return w.astype(dtype)


def embedding_lookup(w, ids):
    """Row gather for plain or quantized embedding tables.  Quantized:
    gather int8 rows, then dequantize just those rows — the table is
    touched at 1 byte/weight."""
    if isinstance(w, QuantizedTensor):
        rows = jnp.take(w.q, ids, axis=0)
        return rows.astype(jnp.float32) * w.scale
    return jnp.take(w, ids, axis=0)
