"""Int8 post-training quantization for the inference path.

`quantize(model)` rewrites a trained Sequential/Graph model's matmul,
conv and embedding weights into `(int8 q, f32 scale)` pairs — symmetric
per-output-channel scales, f32 accumulation at apply time — held in the
params tree as a registered `QuantizedTensor` pytree node, so every
layer of the stack that flattens trees (jit dispatch, hot-swap
verification, checkpoints, the cost registry, ZeRO-free serving) sees
plain int8/f32 leaves with zero special-casing.

The quantized dense path dispatches through
`ops.dequant_matmul.dequant_matmul` — a fused Pallas kernel on TPU
(int8 weight blocks dequantized in-kernel against f32 activations, f32
accumulation), a cache-blocked XLA scan on CPU, and the plain
dequantize-then-dot XLA baseline everywhere else (see
docs/quantization.md for the selection rule).

Post-training and inference-only: `quantize()` drops the optimizer
state; keep the f32 model if you intend to keep training.
"""

from deeplearning4j_tpu.quant.qtensor import QuantizedTensor
from deeplearning4j_tpu.quant.ptq import (
    SCHEME,
    dequantize_tree,
    is_quantized,
    parity_check,
    quantize,
    quantized_bytes,
)

__all__ = [
    "QuantizedTensor",
    "SCHEME",
    "dequantize_tree",
    "is_quantized",
    "parity_check",
    "quantize",
    "quantized_bytes",
]
