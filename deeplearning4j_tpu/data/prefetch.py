"""Device prefetch — the fit loops' software-pipelining stage.

PR 2's per-step spans showed every fit path running strictly serially:
``etl_wait -> host_stage -> dispatch -> device_sync`` — the device idles
while the host pulls and stages the NEXT batch, and the host idles while
the device computes.  `PrefetchIterator` breaks that serialization: a
background thread pulls batch N+1 from the base iterator and stages it
to device (``jax.device_put``) while step N's program runs, feeding a
bounded queue the training thread drains.  The overlap this buys is
exactly the input-pipeline/compute overlap the TF system paper and GSPMD
get their throughput from (PAPERS.md).

The fit loops wrap their iterator in one of these automatically (see
``Model._prefetch_feed``) behind ``flags.prefetch_depth`` — default 2,
0 restores the serial behavior.  Contract:

- **ordering + byte identity**: batches come out in base-iterator order
  with identical values (staging moves bytes, never transforms them);
- **bounded depth**: at most ``depth`` staged batches exist at once, so
  prefetching never pins more than ``depth`` batches of HBM;
- **clean shutdown**: abandoning the iteration (an exception or
  KeyboardInterrupt in the training loop) stops the producer thread and
  joins it — no leaked threads, no orphaned device buffers being
  written to after the loop died;
- **error transparency**: a producer-side exception (decode error, an
  armed ``data.prefetch`` fault) surfaces on the training thread at the
  queue position where it happened, after every batch staged before it;
- **overlap accounting**: each staged batch carries the producer-side
  seconds spent pulling + staging it; the fit loops subtract their own
  queue wait to measure how much of that work was actually hidden
  behind compute (``overlap_seconds`` on the ``train_step`` span,
  ``dl4jtpu_prefetch_overlap_seconds_total`` on the spine).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator

# Attributes _timed_batches reads off a staged batch.  Stage functions
# must copy them from the source batch (tag-preserving staging keeps the
# cache-hit ETL attribution — and the fused-decode routing tag — working
# through the prefetch wrap).  ONE canonical tag list lives in
# data/dataset.py next to the structural batch operations that also
# propagate it.
from deeplearning4j_tpu.data.dataset import BATCH_TAGS


def stage_to_device(batch):
    """Default staging: move every array of a DataSet/MultiDataSet to
    the default device (values unchanged — uint8 stays uint8).  Runs on
    the producer thread so host->HBM DMA overlaps the running step."""
    import jax

    def put(a):
        return None if a is None else jax.device_put(a)

    if isinstance(batch, MultiDataSet):
        staged = MultiDataSet(
            tuple(put(f) for f in batch.features),
            tuple(put(l) for l in batch.labels),
            None if batch.features_masks is None
            else tuple(put(m) for m in batch.features_masks),
            None if batch.labels_masks is None
            else tuple(put(m) for m in batch.labels_masks),
        )
    elif isinstance(batch, DataSet):
        staged = DataSet(
            put(batch.features),
            put(batch.labels),
            put(batch.features_mask),
            put(batch.labels_mask),
        )
    else:
        return batch          # unknown batch type: pull-ahead only
    for tag in BATCH_TAGS:
        v = getattr(batch, tag, None)
        if v is not None:
            setattr(staged, tag, v)
    return staged


class PrefetchIterator(DataSetIterator):
    """Background-thread device prefetch with a bounded queue.

    stage: callable applied to each batch ON THE PRODUCER THREAD
      (default `stage_to_device`); pass `None` for pull-ahead without
      device placement (multi-process feeds stage on the training
      thread via `place_batch` — `put_global` forms global arrays and
      must not run concurrently with the step).
    """

    _END = object()

    def __init__(self, base, depth: int = 2,
                 stage: Optional[Callable] = stage_to_device):
        self._base = base
        self._depth = max(1, int(depth))
        self._stage = stage
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def batch_size(self) -> int:
        return getattr(self._base, "batch_size", 0)

    def reset(self) -> None:
        self.close()
        if hasattr(self._base, "reset"):
            self._base.reset()

    def close(self) -> None:
        """Stop and join the active producer thread (idempotent).  The
        fit loops call this in a finally: an exception mid-epoch must
        not leave a thread pulling batches for a dead loop."""
        stop, thread = self._stop, self._thread
        self._stop, self._thread = None, None
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)

    def __iter__(self) -> Iterator:
        from deeplearning4j_tpu.runtime import faults

        self.close()                      # one producer per iteration
        q: queue.Queue = queue.Queue(maxsize=self._depth)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that gives up once the consumer abandoned the
            # epoch — otherwise the thread (and the staged device
            # buffers it holds) would leak on early exit
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            from deeplearning4j_tpu.observe.metrics import registry

            staged_total = registry().counter(
                "dl4jtpu_prefetch_batches_total"
            )
            try:
                it = iter(self._base)
                while True:
                    t0 = time.perf_counter()
                    # fault site: the producer's pull+stage (armed plans
                    # provoke the flaky-prefetch failure mode; disarmed
                    # this is one attribute check)
                    faults.maybe_fail("data.prefetch")
                    try:
                        batch = next(it)
                    except StopIteration:
                        return
                    if self._stage is not None:
                        batch = self._stage(batch)
                    try:
                        batch._prefetch_stage_s = (
                            time.perf_counter() - t0
                        )
                    except AttributeError:
                        pass              # slotted/foreign batch types
                    staged_total.inc()
                    if not put(batch):
                        return
            except BaseException as e:
                # surfaced in-order on the consumer side: batches staged
                # before the failure still train
                put((self._END, e))
                return
            finally:
                put((self._END, None))

        t = threading.Thread(
            target=produce, name="dl4jtpu-prefetch", daemon=True
        )
        self._stop, self._thread = stop, t
        t.start()
        try:
            while True:
                item = q.get()
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] is self._END:
                    if item[1] is not None:
                        raise item[1]
                    return
                yield item
        finally:
            stop.set()
            t.join(timeout=10.0)
            if self._thread is t:
                self._stop, self._thread = None, None
