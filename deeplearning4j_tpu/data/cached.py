"""Disk-backed batch cache — the `ExistingMiniBatchDataSetIterator` role.

ETL-fed training re-decodes every JPEG each epoch even though the decoded
batches never change (the ETL-fed flagship runs at a fraction of the
synthetic headline for exactly this reason).  `CachedDataSetIterator`
eliminates the re-decode tax: epoch 1 pulls from the base iterator and
writes each batch to disk in its device WIRE format (uint8 stays uint8 —
byte-identical round trip, 1/4 the f32 size); epoch 2+ memory-maps the
saved arrays and never touches the base pipeline again.

Layout under ``cache_dir``::

    b00000.features.npy          one .npy per array — np.load(mmap_mode="r")
    b00000.labels.npy            hands the training loop zero-copy views
    b00000.features_mask.npy     (optional)
    b00000.labels_mask.npy       (optional)
    manifest.json                written ATOMICALLY after a complete epoch

The manifest is the commit point: a process killed mid-population leaves
no manifest, so the next run re-decodes from scratch instead of training
on a silently truncated epoch.  A pre-existing complete cache is used
as-is — the base iterator is never consumed (it may even be None).
"""

from __future__ import annotations

import json
import os
from collections.abc import Iterator
from typing import Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator

_ARRAYS = ("features", "labels", "features_mask", "labels_mask")


def _cache_counter():
    """The spine's cache family (lazy: data/ stays importable without
    observe in odd partial checkouts)."""
    from deeplearning4j_tpu.observe.metrics import registry

    return registry().counter("dl4jtpu_data_cache_batches_total")


class CachedDataSetIterator(DataSetIterator):
    """Cache a base iterator's batches to disk on the first pass, replay
    them via mmap afterwards.

    ``cache_hits`` counts batches served from disk, ``decode_epochs``
    counts full passes that consumed the base iterator — the bench and
    tests assert the decode path is actually skipped, not assumed."""

    def __init__(self, base: Optional[DataSetIterator], cache_dir: str):
        self._base = base
        self.cache_dir = cache_dir
        self.cache_hits = 0
        self.decode_epochs = 0
        os.makedirs(cache_dir, exist_ok=True)
        self._manifest = self._load_manifest()
        if self._manifest is None and base is None:
            raise ValueError(
                f"no complete cache at {cache_dir} and no base iterator "
                "to populate it from"
            )

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.cache_dir, "manifest.json")

    def _load_manifest(self) -> Optional[dict]:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None
        return m if m.get("complete") else None

    @property
    def is_cached(self) -> bool:
        """True once a complete epoch is on disk (replay mode)."""
        return self._manifest is not None

    # -- iteration ---------------------------------------------------------
    @property
    def batch_size(self) -> int:
        if self._manifest is not None:
            return int(self._manifest.get("batch_size", 0))
        return self._base.batch_size

    def reset(self) -> None:
        # replay mode never touches the base pipeline; an incomplete
        # cache restarts population from a clean slate
        if self._manifest is None and self._base is not None:
            self._base.reset()

    def _batch_path(self, i: int, name: str) -> str:
        return os.path.join(self.cache_dir, f"b{i:05d}.{name}.npy")

    def _replay(self) -> Iterator[DataSet]:
        n = int(self._manifest["n_batches"])
        present = self._manifest["arrays"]
        counter = _cache_counter()
        for i in range(n):
            arrs = {}
            for name in _ARRAYS:
                if name in present:
                    # mmap: the training loop reads straight from page
                    # cache; no decode, no copy until device transfer
                    arrs[name] = np.load(
                        self._batch_path(i, name), mmap_mode="r"
                    )
                else:
                    arrs[name] = None
            self.cache_hits += 1
            counter.inc(source="cache")
            ds = DataSet(arrs["features"], arrs["labels"],
                         arrs["features_mask"], arrs["labels_mask"])
            # the fit loops' timed feed reads this tag: hit-path pull
            # time is mmap/page-cache replay, not input-pipeline
            # starvation — it lands on the source="cache" series of
            # dl4jtpu_etl_wait_seconds_total instead of inflating the
            # ETL-wait total PerformanceListener reports
            ds._etl_source = "cache"
            yield ds

    def _populate(self) -> Iterator[DataSet]:
        count = 0
        present: Optional[list] = None
        counter = _cache_counter()
        for batch in self._base:
            arrs = {
                "features": batch.features,
                "labels": batch.labels,
                "features_mask": batch.features_mask,
                "labels_mask": batch.labels_mask,
            }
            here = [n for n in _ARRAYS if arrs[n] is not None]
            if present is None:
                present = here
            elif here != present:
                raise ValueError(
                    "base iterator changed its mask layout mid-epoch "
                    f"(batch {count}: {here} vs {present}); the cache "
                    "needs a uniform batch structure"
                )
            for name in here:
                np.save(self._batch_path(count, name),
                        np.asarray(arrs[name]))
            count += 1
            counter.inc(source="decode")
            yield batch
        if count == 0:
            raise ValueError("base iterator yielded no batches to cache")
        self.decode_epochs += 1
        manifest = {
            "complete": True,
            "n_batches": count,
            "arrays": present,
            "batch_size": int(self._base.batch_size),
        }
        # tmp + rename: the manifest only ever names a fully-written epoch
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, self._manifest_path())
        self._manifest = manifest

    def __iter__(self) -> Iterator[DataSet]:
        if self._manifest is not None:
            return self._replay()
        return self._populate()
