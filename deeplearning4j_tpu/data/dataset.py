"""DataSet / MultiDataSet — the `org.nd4j.linalg.dataset.DataSet` role.

A minibatch: features + labels (+ optional masks for variable-length
sequences, SURVEY.md §5.7).  Stored as numpy on host; transferred to device
inside the compiled step (or prefetched by AsyncDataSetIterator).
MultiDataSet generalizes to multi-input/multi-output models
(ComputationGraph fit path, SURVEY.md §3.2).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: np.ndarray | None = None
    labels_mask: np.ndarray | None = None

    @property
    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_batches(self, batch_size: int) -> list["DataSet"]:
        out = []
        n = self.num_examples
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(
                DataSet(
                    self.features[sl],
                    self.labels[sl],
                    None if self.features_mask is None else self.features_mask[sl],
                    None if self.labels_mask is None else self.labels_mask[sl],
                )
            )
        return out

    def shuffle(self, rng: np.random.Generator) -> "DataSet":
        perm = rng.permutation(self.num_examples)
        return DataSet(
            self.features[perm],
            self.labels[perm],
            None if self.features_mask is None else self.features_mask[perm],
            None if self.labels_mask is None else self.labels_mask[perm],
        )

    @staticmethod
    def merge(batches: list["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([b.features for b in batches]),
            np.concatenate([b.labels for b in batches]),
            None
            if batches[0].features_mask is None
            else np.concatenate([b.features_mask for b in batches]),
            None
            if batches[0].labels_mask is None
            else np.concatenate([b.labels_mask for b in batches]),
        )


@dataclasses.dataclass
class MultiDataSet:
    features: tuple[np.ndarray, ...]
    labels: tuple[np.ndarray, ...]
    features_masks: tuple[np.ndarray | None, ...] | None = None
    labels_masks: tuple[np.ndarray | None, ...] | None = None

    @property
    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    @staticmethod
    def from_dataset(ds: DataSet) -> "MultiDataSet":
        return MultiDataSet(
            (ds.features,),
            (ds.labels,),
            None if ds.features_mask is None else (ds.features_mask,),
            None if ds.labels_mask is None else (ds.labels_mask,),
        )
