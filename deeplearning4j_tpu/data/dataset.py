"""DataSet / MultiDataSet — the `org.nd4j.linalg.dataset.DataSet` role.

A minibatch: features + labels (+ optional masks for variable-length
sequences, SURVEY.md §5.7).  Stored as numpy on host; transferred to device
inside the compiled step (or prefetched by AsyncDataSetIterator).
MultiDataSet generalizes to multi-input/multi-output models
(ComputationGraph fit path, SURVEY.md §3.2).
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Routing/attribution tags carried on batch objects (outside the array
# payload).  Structural batch operations (split, map) and the prefetch
# staging copy them forward so cache-hit ETL attribution and fused
# decode routing survive batch surgery (e.g. recovery's OOM microbatch
# split of a raw-tagged batch; split pieces share the batch's
# _decode_step, so their augmentation keys match the unsplit run).
BATCH_TAGS = ("_etl_source", "_raw_for_device_decode", "_decode_step")


def copy_tags(src, dst):
    """Copy the known batch tags from `src` to `dst` (returns `dst`)."""
    for tag in BATCH_TAGS:
        v = getattr(src, tag, None)
        if v is not None:
            try:
                setattr(dst, tag, v)
            except AttributeError:
                pass              # slotted/foreign batch types
    return dst


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: np.ndarray | None = None
    labels_mask: np.ndarray | None = None

    @property
    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_batches(self, batch_size: int) -> list["DataSet"]:
        out = []
        n = self.num_examples
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(copy_tags(self, DataSet(
                self.features[sl],
                self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl],
            )))
        return out

    def shuffle(self, rng: np.random.Generator) -> "DataSet":
        perm = rng.permutation(self.num_examples)
        return DataSet(
            self.features[perm],
            self.labels[perm],
            None if self.features_mask is None else self.features_mask[perm],
            None if self.labels_mask is None else self.labels_mask[perm],
        )

    @staticmethod
    def merge(batches: list["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([b.features for b in batches]),
            np.concatenate([b.labels for b in batches]),
            None
            if batches[0].features_mask is None
            else np.concatenate([b.features_mask for b in batches]),
            None
            if batches[0].labels_mask is None
            else np.concatenate([b.labels_mask for b in batches]),
        )


@dataclasses.dataclass
class MultiDataSet:
    features: tuple[np.ndarray, ...]
    labels: tuple[np.ndarray, ...]
    features_masks: tuple[np.ndarray | None, ...] | None = None
    labels_masks: tuple[np.ndarray | None, ...] | None = None

    @property
    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    @staticmethod
    def from_dataset(ds: DataSet) -> "MultiDataSet":
        return MultiDataSet(
            (ds.features,),
            (ds.labels,),
            None if ds.features_mask is None else (ds.features_mask,),
            None if ds.labels_mask is None else (ds.labels_mask,),
        )

    def split_batches(self, batch_size: int) -> list["MultiDataSet"]:
        out = []
        n = self.num_examples
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))

            def cut(arrays):
                if arrays is None:
                    return None
                return tuple(None if a is None else a[sl] for a in arrays)

            out.append(copy_tags(self, MultiDataSet(
                cut(self.features),
                cut(self.labels),
                cut(self.features_masks),
                cut(self.labels_masks),
            )))
        return out


def map_batch(batch, fn, *, masks: bool = True):
    """A structural copy of a DataSet/MultiDataSet with `fn` applied to
    every feature/label array — masks too unless ``masks=False`` (they
    then carry over untouched).  None entries and non-batch objects
    pass through.  The single batch traversal behind example slicing
    (recovery's microbatch resume) and poison-fill (the injected
    corrupt decoder): knowledge of batch structure stays in this
    module."""
    def ap(a):
        return None if a is None else fn(a)

    if isinstance(batch, DataSet):
        return copy_tags(batch, DataSet(
            ap(batch.features), ap(batch.labels),
            ap(batch.features_mask) if masks else batch.features_mask,
            ap(batch.labels_mask) if masks else batch.labels_mask,
        ))
    if isinstance(batch, MultiDataSet):
        def apt(arrays, mask_group=False):
            if arrays is None:
                return None
            if mask_group and not masks:
                return arrays
            return tuple(ap(a) for a in arrays)

        return copy_tags(batch, MultiDataSet(
            apt(batch.features), apt(batch.labels),
            apt(batch.features_masks, mask_group=True),
            apt(batch.labels_masks, mask_group=True),
        ))
    return batch


def named_arrays(batch, *, masks: bool = True) -> dict:
    """Flatten a DataSet/MultiDataSet into a stable name->np.ndarray
    dict — ``features``/``labels``/``*_mask``, MultiDataSet entries
    suffixed ``_<i>``; None entries dropped; non-batch objects give {}.
    The npz/scan view of a batch (quarantine records, non-finite input
    screening)."""
    out: dict = {}
    if isinstance(batch, DataSet):
        pairs = [("features", batch.features), ("labels", batch.labels)]
        if masks:
            pairs += [("features_mask", batch.features_mask),
                      ("labels_mask", batch.labels_mask)]
        for name, a in pairs:
            if a is not None:
                out[name] = np.asarray(a)
    elif isinstance(batch, MultiDataSet):
        groups = [("features", batch.features), ("labels", batch.labels)]
        if masks:
            groups += [("features_mask", batch.features_masks or ()),
                       ("labels_mask", batch.labels_masks or ())]
        for group, arrays in groups:
            for i, a in enumerate(arrays):
                if a is not None:
                    out[f"{group}_{i}"] = np.asarray(a)
    return out
