from deeplearning4j_tpu.data.cached import CachedDataSetIterator
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterator import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    NumpyDataSetIterator,
)
from deeplearning4j_tpu.data.prefetch import PrefetchIterator

__all__ = [
    "DataSet",
    "MultiDataSet",
    "DataSetIterator",
    "NumpyDataSetIterator",
    "ExistingDataSetIterator",
    "AsyncDataSetIterator",
    "CachedDataSetIterator",
    "PrefetchIterator",
]
