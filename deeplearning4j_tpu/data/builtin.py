"""Built-in dataset iterators — the MnistDataSetIterator / CifarDataSetIterator role.

The reference downloads MNIST/CIFAR on first use.  This environment has no
network, so each built-in first looks for local copies (IDX/np files under
$DL4J_TPU_DATA_DIR, ./data, or ~/.dl4j_tpu) and otherwise falls back to a
DETERMINISTIC PROCEDURAL dataset of the same shape and difficulty profile:
digit glyphs rendered from a 5x7 font with random shift/scale/noise/elastic
jitter.  The synthetic task is honest — classes overlap in pixel space and
require learned features (a linear model gets ~90%, LeNet >99%) — so
convergence and throughput numbers remain meaningful.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator

# 5x7 digit glyphs (classic font), 1 bit per pixel, row-major top-down.
_DIGIT_GLYPHS = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _data_dirs() -> list[Path]:
    dirs = []
    if os.environ.get("DL4J_TPU_DATA_DIR"):
        dirs.append(Path(os.environ["DL4J_TPU_DATA_DIR"]))
    dirs += [Path("./data"), Path.home() / ".dl4j_tpu"]
    return dirs


def _read_idx(path: Path) -> np.ndarray:
    if path.suffix != ".gz":
        # native decoder (runtime/native.py) when built — the DataVec-role
        # native hot path; ungzipped files only
        from deeplearning4j_tpu.runtime import native

        if native.available():
            try:
                return native.idx_read_u8(str(path))
            except (IOError, RuntimeError):
                pass                      # fall back to the numpy path
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _u8_scale(x: np.ndarray, scale: float = 1.0 / 255.0,
              shift: float = 0.0) -> np.ndarray:
    """uint8 -> float32 * scale + shift, natively when built."""
    from deeplearning4j_tpu.runtime import native

    if x.dtype == np.uint8 and native.available():
        try:
            return native.u8_to_f32_scaled(x, scale, shift)
        except (IOError, RuntimeError):
            pass
    return x.astype(np.float32) * scale + shift


def _find_mnist() -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    names = [
        ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
         "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    ]
    for d in _data_dirs():
        for base in (d, d / "mnist", d / "MNIST"):
            for quad in names:
                paths = []
                ok = True
                for n in quad:
                    found = None
                    for cand in (base / n, base / (n + ".gz")):
                        if cand.exists():
                            found = cand
                            break
                    if found is None:
                        ok = False
                        break
                    paths.append(found)
                if ok:
                    xi, yi, xt, yt = (_read_idx(p) for p in paths)
                    return xi, yi, xt, yt
    return None


def synthetic_mnist(
    n: int, seed: int = 0, image_size: int = 28
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-like digits: glyph + shift + scale + noise.

    Returns (images [n, s, s, 1] float32 in [0,1], labels int [n]).
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    glyphs = np.zeros((10, 7, 5), np.float32)
    for d, rows in _DIGIT_GLYPHS.items():
        glyphs[d] = np.array([[int(c) for c in r] for r in rows], np.float32)
    images = np.zeros((n, image_size, image_size, 1), np.float32)
    for i, lab in enumerate(labels):
        g = glyphs[lab]
        # upscale by a per-example factor (2..3) with nearest neighbor
        scale = rng.integers(2, 4)
        up = np.repeat(np.repeat(g, scale * 2, axis=0), scale * 2, axis=1)
        # thin random erosion: drop some "on" pixels to mimic stroke noise
        keep = rng.random(up.shape) > 0.08
        up = up * keep
        h, w = up.shape
        h, w = min(h, image_size), min(w, image_size)
        up = up[:h, :w]
        max_r, max_c = image_size - h, image_size - w
        r0 = rng.integers(0, max_r + 1)
        c0 = rng.integers(0, max_c + 1)
        images[i, r0 : r0 + h, c0 : c0 + w, 0] = up
    # intensity jitter + background noise
    images *= rng.uniform(0.7, 1.0, (n, 1, 1, 1)).astype(np.float32)
    images += rng.normal(0, 0.08, images.shape).astype(np.float32)
    return np.clip(images, 0.0, 1.0), labels.astype(np.int64)


class MnistDataSetIterator(DataSetIterator):
    """MNIST minibatches, NHWC [B,28,28,1] in [0,1], one-hot labels.

    Real data when found locally (IDX files); deterministic synthetic
    otherwise (`is_synthetic` says which).
    """

    NUM_CLASSES = 10

    def __init__(
        self,
        batch_size: int,
        train: bool = True,
        seed: int = 123,
        num_examples: int | None = None,
        flatten: bool = False,
    ):
        self._batch = batch_size
        self._flatten = flatten
        found = _find_mnist()
        if found is not None:
            xi, yi, xt, yt = found
            x, y = (xi, yi) if train else (xt, yt)
            self.is_synthetic = False
            x = _u8_scale(x)[..., None]
            y = y.astype(np.int64)
        else:
            default_n = 60000 if train else 10000
            n = num_examples or default_n
            x, y = synthetic_mnist(n, seed=seed if train else seed + 777)
            self.is_synthetic = True
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        if flatten:
            x = x.reshape(x.shape[0], -1)
        self._x = x
        self._y = np.eye(self.NUM_CLASSES, dtype=np.float32)[y]
        self._rng = np.random.default_rng(seed)
        self._shuffle = train

    @property
    def batch_size(self) -> int:
        return self._batch

    @property
    def num_examples(self) -> int:
        return len(self._x)

    def reset(self) -> None:
        pass

    def __iter__(self):
        yield from _iterate_batches(self._x, self._y, self._batch, self._shuffle, self._rng)


def _iterate_batches(x, y, batch, shuffle, rng):
    """Training (shuffle=True) drops the final short batch to keep step
    shapes static; evaluation (shuffle=False) yields it so no example is
    silently excluded from metrics."""
    idx = np.arange(len(x))
    if shuffle:
        rng.shuffle(idx)
    n_full = len(idx) // batch
    for i in range(n_full):
        sl = idx[i * batch : (i + 1) * batch]
        yield DataSet(x[sl], y[sl])
    tail = idx[n_full * batch :]
    if len(tail) and (not shuffle or n_full == 0):
        yield DataSet(x[tail], y[tail])


def synthetic_cifar(n: int, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """CIFAR-shaped procedural 10-class dataset [n,32,32,3]."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    images = rng.normal(0.45, 0.15, (n, 32, 32, 3)).astype(np.float32)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 31.0
    for i, lab in enumerate(labels):
        # class-conditional chromatic gradient + textured patch
        a, b = (lab % 5) / 4.0, (lab // 5) / 1.0
        images[i, :, :, 0] += 0.3 * (a * xx + (1 - a) * yy)
        images[i, :, :, 1] += 0.3 * (b * (1 - xx))
        r0, c0 = (lab * 3) % 24, (lab * 7) % 24
        images[i, r0 : r0 + 8, c0 : c0 + 8, 2] += 0.4
    return np.clip(images, 0, 1), labels.astype(np.int64)


class CifarDataSetIterator(DataSetIterator):
    """CIFAR-10-shaped minibatches (synthetic fallback, local npz when found)."""

    NUM_CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True, seed: int = 321,
                 num_examples: int | None = None):
        self._batch = batch_size
        x = y = None
        self.is_synthetic = False
        for d in _data_dirs():
            f = d / ("cifar10_train.npz" if train else "cifar10_test.npz")
            if f.exists():
                data = np.load(f)
                x, y = data["x"].astype(np.float32), data["y"].astype(np.int64)
                if x.max() > 1.5:
                    x = x / 255.0
                if x.shape[1] == 3:  # NCHW on disk -> NHWC
                    x = x.transpose(0, 2, 3, 1)
                break
        if x is None:
            n = num_examples or (50000 if train else 10000)
            x, y = synthetic_cifar(n, seed=seed if train else seed + 999)
            self.is_synthetic = True
        if num_examples is not None:
            x, y = x[:num_examples], y[:num_examples]
        self._x = x
        self._y = np.eye(self.NUM_CLASSES, dtype=np.float32)[y]
        self._rng = np.random.default_rng(seed)
        self._shuffle = train

    @property
    def batch_size(self) -> int:
        return self._batch

    @property
    def num_examples(self) -> int:
        return len(self._x)

    def reset(self) -> None:
        pass

    def __iter__(self):
        yield from _iterate_batches(self._x, self._y, self._batch, self._shuffle, self._rng)
