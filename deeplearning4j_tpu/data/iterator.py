"""DataSetIterator SPI + async prefetch.

The reference's `DataSetIterator` contract and `AsyncDataSetIterator`
(background prefetch thread feeding a bounded queue — the input-pipeline
overlap mechanism, SURVEY.md §2.2).  TPU-native, the async iterator also
moves batches to device ahead of time (`jax.device_put`) so the compiled
step never waits on host→HBM DMA — the double-buffering idiom.
"""

from __future__ import annotations

from collections.abc import Iterable, Iterator

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataSetIterator:
    """Iterable over DataSet minibatches; resettable."""

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    @property
    def batch_size(self) -> int:
        raise NotImplementedError


class NumpyDataSetIterator(DataSetIterator):
    """In-memory (features, labels) arrays -> shuffled minibatches."""

    def __init__(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if len(features) == 0:
            raise ValueError("empty dataset")
        if len(features) != len(labels):
            raise ValueError(
                f"features ({len(features)}) and labels ({len(labels)}) "
                "have different numbers of examples"
            )
        self._data = DataSet(np.asarray(features), np.asarray(labels))
        self._batch = int(batch_size)
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._drop_last = drop_last

    @property
    def batch_size(self) -> int:
        return self._batch

    def reset(self) -> None:
        pass  # stateless between epochs; shuffling re-drawn per __iter__

    def __iter__(self) -> Iterator[DataSet]:
        ds = self._data.shuffle(self._rng) if self._shuffle else self._data
        batches = ds.split_batches(self._batch)
        if self._drop_last:
            kept = [b for b in batches if b.num_examples == self._batch]
            # never drop EVERYTHING: a dataset smaller than batch_size still
            # trains on its single short batch
            batches = kept if kept else batches
        yield from batches


class ExistingDataSetIterator(DataSetIterator):
    """Wraps any iterable of DataSet (the reference's ExistingDataSetIterator)."""

    def __init__(self, batches: Iterable[DataSet]):
        self._batches = list(batches)

    @property
    def batch_size(self) -> int:
        return self._batches[0].num_examples if self._batches else 0

    def reset(self) -> None:
        pass

    def __iter__(self) -> Iterator[DataSet]:
        return iter(self._batches)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with optional device placement.

    Role of the reference's AsyncDataSetIterator (queue of prefetched
    batches).  With device_put=True, batches are transferred to the default
    device from the producer thread, overlapping host ETL + DMA with the
    running step.

    Since the pipelined fit loop landed this is a thin facade over
    `data/prefetch.PrefetchIterator` — ONE producer-thread
    implementation carries all the hardening (bounded queue, in-order
    error sentinel, close()-joins-the-thread shutdown, the
    `data.prefetch` fault site, overlap stage tags): `queue_size` maps
    to `depth`, `device_put=True` maps to the `stage_to_device` hook.
    """

    def __init__(self, base: DataSetIterator, queue_size: int = 2, device_put: bool = True):
        from deeplearning4j_tpu.data.prefetch import (
            PrefetchIterator, stage_to_device,
        )

        self._base = base
        self._prefetch = PrefetchIterator(
            base,
            depth=queue_size,
            stage=stage_to_device if device_put else None,
        )

    @property
    def batch_size(self) -> int:
        return self._base.batch_size

    def reset(self) -> None:
        self._prefetch.reset()

    def close(self) -> None:
        self._prefetch.close()

    def __iter__(self) -> Iterator[DataSet]:
        return iter(self._prefetch)
