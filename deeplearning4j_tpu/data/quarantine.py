"""Bounded on-disk quarantine for poison batches.

One corrupt record — a decoder emitting NaNs, a truncated image, a
shape-drifted example — used to kill an entire run: the fit loop either
raised out of the batch pull or trained a NaN into the params.  The
`RecoveryPolicy` (train/recovery.py) diverts such batches HERE instead:
the bytes (when the batch object survived) plus a JSON metadata record
land in a directory a human can replay offline, the run continues, and
``dl4jtpu_quarantined_batches_total{reason=...}`` says how often.

Bounded by design: at most ``cap`` entries are ever written (a fully
poisoned feed must fill a quota, not a disk), after which `put()`
returns None and the caller decides whether to keep dropping or to
fail loudly — `RecoveryPolicy` fails loudly.

Layout per entry (``q_<seq>`` naming, seq monotonic per store)::

    q_00000.json   {"reason", "error", "time", "shapes", "has_bytes"}
    q_00000.npz    features/labels/masks arrays (only when a batch
                   object was available — pull-time failures have no
                   bytes to save)
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")


class QuarantineStore:
    """Directory of quarantined batches, capped at `cap` entries.

    Single-writer (the fit thread's RecoveryPolicy); `entries()` may be
    read any time.  Existing ``q_*.json`` files found at construction
    count against the cap — a restarted run does not get a fresh disk
    budget for the same poisoned feed.
    """

    def __init__(self, directory: str, cap: int = 16):
        if cap < 1:
            raise ValueError("quarantine cap must be >= 1")
        self.directory = directory
        self.cap = int(cap)
        self._seq = 0
        try:
            existing = [
                n for n in os.listdir(directory)
                if n.startswith("q_") and n.endswith(".json")
            ]
        except FileNotFoundError:
            existing = []
        if existing:
            self._seq = 1 + max(
                int(n[2:-5]) for n in existing if n[2:-5].isdigit()
            )

    def __len__(self) -> int:
        try:
            return sum(
                1 for n in os.listdir(self.directory)
                if n.startswith("q_") and n.endswith(".json")
            )
        except FileNotFoundError:
            return 0

    @property
    def full(self) -> bool:
        return len(self) >= self.cap

    def put(self, reason: str, batch=None,
            error: Optional[BaseException] = None,
            meta: Optional[dict] = None) -> Optional[str]:
        """Quarantine one batch; returns the metadata path, or None when
        the cap is reached (nothing written — the caller escalates)."""
        from deeplearning4j_tpu.data.dataset import named_arrays

        if self.full:
            return None
        os.makedirs(self.directory, exist_ok=True)
        stem = os.path.join(self.directory, f"q_{self._seq:05d}")
        self._seq += 1
        arrays = named_arrays(batch) if batch is not None else {}
        record = {
            "reason": reason,
            "error": (f"{type(error).__name__}: {error}"
                      if error is not None else None),
            "time": time.time(),
            "shapes": {k: list(v.shape) for k, v in arrays.items()},
            "has_bytes": bool(arrays),
        }
        if meta:
            record.update(meta)
        if arrays:
            with open(stem + ".npz", "wb") as f:
                np.savez(f, **arrays)
        path = stem + ".json"
        with open(path, "w") as f:
            json.dump(record, f, indent=1, default=str)
        log.warning("quarantined batch -> %s (%s)", path, reason)
        return path

    def entries(self) -> list[dict]:
        """Metadata records on disk, oldest first (each carries its
        ``path``; sibling ``.npz`` holds the bytes when has_bytes)."""
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith("q_") and n.endswith(".json")
            )
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            p = os.path.join(self.directory, n)
            try:
                with open(p) as f:
                    rec = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                log.debug("unreadable quarantine record %s: %s", p, e)
                continue
            rec["path"] = p
            out.append(rec)
        return out
