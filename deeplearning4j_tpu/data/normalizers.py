"""Data normalizers — the `org.nd4j.linalg.dataset.api.preprocessor` role.

fit(iterator) accumulates statistics; transform/preprocess applies them;
save/restore persists them (the reference serializes normalizers into the
model zip so serving uses the exact training statistics).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import DataSetIterator


class Normalizer:
    def fit(self, iterator) -> "Normalizer":
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def revert_features(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- persistence -------------------------------------------------------
    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict) -> None:
        raise NotImplementedError

    def save(self, path: str) -> None:
        Path(path).write_text(
            json.dumps({"type": type(self).__name__, **self.state_dict()})
        )

    def device_spec(self):
        """The datavec/device.py transform spec this normalizer lowers
        to (stats baked in as program constants), or None when the
        normalizer has no device lowering — NormalizingIterator
        advertises it so fit() can fuse the normalization into the
        step program."""
        return None

    @staticmethod
    def restore(path: str) -> "Normalizer":
        d = json.loads(Path(path).read_text())
        cls = {c.__name__: c for c in (NormalizerStandardize, NormalizerMinMaxScaler,
                                       ImagePreProcessingScaler)}[d.pop("type")]
        n = cls()
        n.load_state_dict(d)
        return n


class NormalizerStandardize(Normalizer):
    """Per-feature zero-mean unit-variance (fit via streaming moments)."""

    def __init__(self):
        self.mean: np.ndarray | None = None
        self.std: np.ndarray | None = None

    def fit(self, iterator):
        count = 0
        s1 = s2 = None
        for batch in iterator:
            f = batch.features.astype(np.float64)
            axes = tuple(range(f.ndim - 1))
            b1 = f.sum(axis=axes)
            b2 = (f**2).sum(axis=axes)
            n = int(np.prod([f.shape[a] for a in axes]))
            s1 = b1 if s1 is None else s1 + b1
            s2 = b2 if s2 is None else s2 + b2
            count += n
        iterator.reset()
        self.mean = (s1 / count).astype(np.float32)
        var = s2 / count - (s1 / count) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        return self

    def transform(self, ds: DataSet) -> DataSet:
        f = (ds.features - self.mean) / self.std
        return DataSet(f.astype(np.float32), ds.labels, ds.features_mask, ds.labels_mask)

    def revert_features(self, features):
        return features * self.std + self.mean

    def device_spec(self):
        if self.mean is None:
            return None                   # not fitted yet
        from deeplearning4j_tpu.datavec.device import Standardize

        return Standardize(self.mean, self.std)

    def state_dict(self):
        return {"mean": self.mean.tolist(), "std": self.std.tolist()}

    def load_state_dict(self, d):
        self.mean = np.asarray(d["mean"], np.float32)
        self.std = np.asarray(d["std"], np.float32)


class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [lo, hi] using per-feature min/max."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi
        self.min: np.ndarray | None = None
        self.max: np.ndarray | None = None

    def fit(self, iterator):
        mn = mx = None
        for batch in iterator:
            f = batch.features
            axes = tuple(range(f.ndim - 1))
            bmn, bmx = f.min(axis=axes), f.max(axis=axes)
            mn = bmn if mn is None else np.minimum(mn, bmn)
            mx = bmx if mx is None else np.maximum(mx, bmx)
        iterator.reset()
        self.min, self.max = mn.astype(np.float32), mx.astype(np.float32)
        return self

    def transform(self, ds: DataSet) -> DataSet:
        rng = np.maximum(self.max - self.min, 1e-12)
        f = (ds.features - self.min) / rng * (self.hi - self.lo) + self.lo
        return DataSet(f.astype(np.float32), ds.labels, ds.features_mask, ds.labels_mask)

    def revert_features(self, features):
        rng = np.maximum(self.max - self.min, 1e-12)
        return (features - self.lo) / (self.hi - self.lo) * rng + self.min

    def device_spec(self):
        if self.min is None:
            return None                   # not fitted yet
        from deeplearning4j_tpu.datavec.device import MinMaxScale

        return MinMaxScale(self.min, self.max, self.lo, self.hi)

    def state_dict(self):
        return {"lo": self.lo, "hi": self.hi,
                "min": self.min.tolist(), "max": self.max.tolist()}

    def load_state_dict(self, d):
        self.lo, self.hi = d["lo"], d["hi"]
        self.min = np.asarray(d["min"], np.float32)
        self.max = np.asarray(d["max"], np.float32)


class ImagePreProcessingScaler(Normalizer):
    """uint8 [0,255] images -> [lo,hi] floats (stateless; fit is a no-op)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi

    def fit(self, iterator):
        return self

    def transform(self, ds: DataSet) -> DataSet:
        scale = (self.hi - self.lo) / 255.0
        x = np.asarray(ds.features)
        if x.dtype == np.uint8:
            # native hot path (runtime/native.py) when built
            from deeplearning4j_tpu.runtime import native

            if native.available():
                try:
                    f = native.u8_to_f32_scaled(x, scale, self.lo)
                    return DataSet(f, ds.labels, ds.features_mask,
                                   ds.labels_mask)
                except (IOError, RuntimeError):
                    pass
        f = x.astype(np.float32) * scale + self.lo
        return DataSet(f, ds.labels, ds.features_mask, ds.labels_mask)

    def device_spec(self):
        from deeplearning4j_tpu.datavec.device import Scale

        return Scale((self.hi - self.lo) / 255.0, self.lo)

    def revert_features(self, features):
        return (features - self.lo) / (self.hi - self.lo) * 255.0

    def state_dict(self):
        return {"lo": self.lo, "hi": self.hi}

    def load_state_dict(self, d):
        self.lo, self.hi = d["lo"], d["hi"]


class NormalizingIterator(DataSetIterator):
    """Wrap an iterator so every batch passes through a fitted normalizer
    (the reference's iterator.setPreProcessor(normalizer) pattern).

    Advertises the normalizer's device lowering (``device_chain`` /
    ``raw()``): fit() fuses the normalization into the step program and
    pulls the base iterator's undecoded batches instead, when the
    lowering exists."""

    def __init__(self, base, normalizer: Normalizer):
        self._base = base
        self._norm = normalizer

    @property
    def batch_size(self):
        return self._base.batch_size

    @property
    def device_chain(self):
        spec = self._norm.device_spec()
        if spec is None:
            return None
        from deeplearning4j_tpu.datavec.device import TransformChain

        # memoized per spec fingerprint: a fresh chain every access
        # would defeat try_lower's on-chain lowering cache (each fit
        # would re-pay the standalone decode calibration), while a
        # refitted normalizer changes the fingerprint and invalidates
        fp = spec.fingerprint()
        cached = getattr(self, "_chain_cache", None)
        if cached is None or cached[0] != fp:
            self._chain_cache = (fp, TransformChain(features=(spec,)))
        return self._chain_cache[1]

    def raw(self):
        return self._base

    def reset(self):
        self._base.reset()

    def __iter__(self):
        for batch in self._base:
            yield self._norm.transform(batch)
