"""Reinforcement learning — the RL4J role.

Reference: `rl4j-core` (SURVEY.md §2.2 "RL4J"): Q-learning
(`QLearningDiscrete`, double/dueling DQN), actor-critic (A3C/A2C),
policies, experience replay, MDP abstractions and environment bindings.

TPU-native shape: networks are built from the framework's own layer
configs (pure init/apply), and each algorithm owns ONE jitted update step
(TD loss or actor-critic loss, gradients, optimizer) — the whole learning
step is a single XLA program, like the supervised models' compiled fit.
Environments are in-process numpy MDPs (`CartPole`, `GridWorld`) — the
gym/malmo bindings role without a network dependency.

    from deeplearning4j_tpu.rl import DQN, CartPole
    agent = DQN(obs_dim=4, n_actions=2, hidden=(64, 64), double=True)
    history = agent.train(CartPole(), episodes=150)
    action = agent.play(obs)                      # greedy policy
"""

from deeplearning4j_tpu.rl.mdp import MDP, CartPole, GridWorld
from deeplearning4j_tpu.rl.replay import ExperienceReplay
from deeplearning4j_tpu.rl.policy import (
    BoltzmannPolicy,
    EpsilonGreedyPolicy,
    GreedyPolicy,
)
from deeplearning4j_tpu.rl.dqn import DQN
from deeplearning4j_tpu.rl.a2c import A2C

__all__ = [
    "MDP",
    "CartPole",
    "GridWorld",
    "ExperienceReplay",
    "EpsilonGreedyPolicy",
    "GreedyPolicy",
    "BoltzmannPolicy",
    "DQN",
    "A2C",
]
