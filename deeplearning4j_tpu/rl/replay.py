"""Experience replay — `org.deeplearning4j.rl4j.experience` role.

Circular numpy buffers with uniform sampling; stores (s, a, r, s', done)
transitions.  Host-side on purpose: collection is sequential/interactive;
only the SAMPLED batch crosses to the device inside the jitted update.
"""

from __future__ import annotations

import numpy as np


class ExperienceReplay:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self._rng = np.random.default_rng(seed)
        self._next = 0
        self.size = 0

    def add(self, obs, action, reward, next_obs, done) -> None:
        i = self._next
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.next_obs[i] = next_obs
        self.dones[i] = float(done)
        self._next = (i + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int):
        idx = self._rng.integers(0, self.size, batch_size)
        return (
            self.obs[idx],
            self.actions[idx],
            self.rewards[idx],
            self.next_obs[idx],
            self.dones[idx],
        )

    def __len__(self) -> int:
        return self.size
