"""DQN — `QLearningDiscrete` (+ double/dueling variants) role.

The torso reuses the framework's Dense layer configs (pure init/apply);
the TD update — forward on both online and target params, double-DQN
action selection, Huber TD loss, gradients, Adam — is ONE jitted XLA
program per step (the reference interprets this op-by-op through the
executioner; SURVEY.md §3.1's op-at-a-time overhead is exactly what the
compiled step removes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.layers import Dense
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import EpsilonGreedyPolicy
from deeplearning4j_tpu.rl.replay import ExperienceReplay
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.runtime.rng import SeedStream


def _build_torso(obs_dim: int, hidden: tuple[int, ...], key) -> tuple[list, dict]:
    layers, params = [], {}
    itype = InputType.feed_forward(obs_dim)
    for i, h in enumerate(hidden):
        cfg = Dense(name=f"h{i}", n_out=h, activation=Activation.RELU)
        p, _ = cfg.init(jax.random.fold_in(key, i), itype)
        layers.append(cfg)
        params[cfg.name] = p
        itype = cfg.output_type(itype)
    return layers, params


def _torso_apply(layers, params, x):
    for cfg in layers:
        x, _ = cfg.apply(params[cfg.name], {}, x)
    return x


class DQN:
    def __init__(
        self,
        obs_dim: int,
        n_actions: int,
        hidden: tuple[int, ...] = (64, 64),
        gamma: float = 0.99,
        lr: float = 1e-3,
        batch_size: int = 64,
        replay_capacity: int = 20000,
        target_update_every: int = 200,
        double: bool = True,
        dueling: bool = False,
        policy: EpsilonGreedyPolicy | None = None,
        seed: int = 0,
    ):
        self.obs_dim, self.n_actions = obs_dim, n_actions
        self.gamma = gamma
        self.batch_size = batch_size
        self.target_update_every = target_update_every
        self.double = double
        self.dueling = dueling
        self.policy = policy or EpsilonGreedyPolicy()
        self._np_rng = np.random.default_rng(seed)

        stream = SeedStream(seed)
        self.layers, torso = _build_torso(obs_dim, hidden, stream.key("torso"))
        d = hidden[-1] if hidden else obs_dim
        k = stream.key("heads")
        if dueling:
            k1, k2 = jax.random.split(k)
            heads = {
                "value": {"W": jax.random.normal(k1, (d, 1)) * (1 / np.sqrt(d)),
                          "b": jnp.zeros((1,))},
                "adv": {"W": jax.random.normal(k2, (d, n_actions)) * (1 / np.sqrt(d)),
                        "b": jnp.zeros((n_actions,))},
            }
        else:
            heads = {
                "q": {"W": jax.random.normal(k, (d, n_actions)) * (1 / np.sqrt(d)),
                      "b": jnp.zeros((n_actions,))},
            }
        self.params = {"torso": torso, "heads": heads}
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._tx = optax.adam(lr)
        self.opt_state = self._tx.init(self.params)
        self.replay = ExperienceReplay(replay_capacity, obs_dim, seed)
        self.global_step = 0
        self._update = self._make_update()
        self._qfn = jax.jit(self._q_values)

    # -- pure functions ----------------------------------------------------
    def _q_values(self, params, obs):
        h = _torso_apply(self.layers, params["torso"], obs)
        heads = params["heads"]
        if self.dueling:
            v = h @ heads["value"]["W"] + heads["value"]["b"]
            a = h @ heads["adv"]["W"] + heads["adv"]["b"]
            return v + a - jnp.mean(a, axis=-1, keepdims=True)
        return h @ heads["q"]["W"] + heads["q"]["b"]

    def _make_update(self):
        @jax.jit
        def update(params, target_params, opt_state, obs, actions, rewards,
                   next_obs, dones):
            if self.double:
                next_online = self._q_values(params, next_obs)
                next_actions = jnp.argmax(next_online, axis=-1)
                next_q_all = self._q_values(target_params, next_obs)
                next_q = jnp.take_along_axis(
                    next_q_all, next_actions[:, None], axis=-1
                )[:, 0]
            else:
                next_q = jnp.max(
                    self._q_values(target_params, next_obs), axis=-1
                )
            targets = rewards + self.gamma * (1.0 - dones) * next_q
            targets = jax.lax.stop_gradient(targets)

            def loss_fn(p):
                q = self._q_values(p, obs)
                picked = jnp.take_along_axis(
                    q, actions[:, None].astype(jnp.int32), axis=-1
                )[:, 0]
                return jnp.mean(optax.huber_loss(picked, targets))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return update

    # -- interaction -------------------------------------------------------
    def act(self, obs: np.ndarray) -> int:
        q = np.asarray(self._qfn(self.params, obs[None]))[0]
        return self.policy.select(q, self._np_rng, self.global_step)

    def play(self, obs: np.ndarray) -> int:
        """Greedy action (the trained Policy role)."""
        return int(np.argmax(np.asarray(self._qfn(self.params, obs[None]))[0]))

    def train(self, mdp: MDP, episodes: int = 100,
              warmup_steps: int = 500) -> list[float]:
        """Returns per-episode undiscounted returns."""
        history = []
        for _ in range(episodes):
            obs = mdp.reset()
            ep_return, done = 0.0, False
            while not done:
                action = self.act(obs)
                next_obs, reward, done, _ = mdp.step(action)
                self.replay.add(obs, action, reward, next_obs, done)
                obs = next_obs
                ep_return += reward
                self.global_step += 1
                if len(self.replay) >= max(warmup_steps, self.batch_size):
                    batch = self.replay.sample(self.batch_size)
                    self.params, self.opt_state, _ = self._update(
                        self.params, self.target_params, self.opt_state, *batch
                    )
                    if self.global_step % self.target_update_every == 0:
                        self.target_params = jax.tree.map(
                            jnp.copy, self.params
                        )
            history.append(ep_return)
        return history
