"""MDP abstraction + built-in environments.

Reference roles: `org.deeplearning4j.rl4j.mdp.MDP` and the gym/malmo/ale
environment bindings.  No network here, so the classic control tasks are
implemented directly (same dynamics the gym classics use) — everything an
RL algorithm needs to be tested end-to-end in-process.
"""

from __future__ import annotations

import numpy as np


class MDP:
    """reset() -> obs; step(action) -> (obs, reward, done, info)."""

    obs_dim: int
    n_actions: int

    def reset(self, seed=None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int):
        raise NotImplementedError


class CartPole(MDP):
    """Cart-pole balancing (the classic control dynamics: Barto, Sutton &
    Anderson 1983 — the same task gym's CartPole-v1 wraps).  Reward +1 per
    step; episode ends on |x| > 2.4, |theta| > 12deg, or max_steps."""

    obs_dim = 4
    n_actions = 2

    GRAVITY = 9.8
    M_CART, M_POLE = 1.0, 0.1
    L_HALF = 0.5                    # half pole length
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * np.pi / 180
    X_LIMIT = 2.4

    def __init__(self, max_steps: int = 500, seed: int = 0):
        self.max_steps = max_steps
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._t = 0

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._t = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, th, th_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        total_m = self.M_CART + self.M_POLE
        pole_ml = self.M_POLE * self.L_HALF
        cos_t, sin_t = np.cos(th), np.sin(th)
        temp = (force + pole_ml * th_dot**2 * sin_t) / total_m
        th_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.L_HALF * (4.0 / 3.0 - self.M_POLE * cos_t**2 / total_m)
        )
        x_acc = temp - pole_ml * th_acc * cos_t / total_m
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        th += self.DT * th_dot
        th_dot += self.DT * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        done = (
            abs(x) > self.X_LIMIT
            or abs(th) > self.THETA_LIMIT
            or self._t >= self.max_steps
        )
        return self._state.astype(np.float32), 1.0, bool(done), {}


class GridWorld(MDP):
    """Deterministic n x n grid: start top-left, goal bottom-right,
    actions (up, down, left, right), -0.01 per step, +1 at the goal.
    Observation: one-hot cell index.  Optimal return is known in closed
    form — the convergence oracle for the DQN test."""

    n_actions = 4

    def __init__(self, n: int = 4, max_steps: int = 100):
        self.n = n
        self.obs_dim = n * n
        self.max_steps = max_steps
        self._pos = (0, 0)
        self._t = 0

    def _obs(self):
        v = np.zeros(self.obs_dim, np.float32)
        v[self._pos[0] * self.n + self._pos[1]] = 1.0
        return v

    def reset(self, seed=None):
        self._pos, self._t = (0, 0), 0
        return self._obs()

    def step(self, action: int):
        r, c = self._pos
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][action]
        self._pos = (
            min(max(r + dr, 0), self.n - 1),
            min(max(c + dc, 0), self.n - 1),
        )
        self._t += 1
        at_goal = self._pos == (self.n - 1, self.n - 1)
        reward = 1.0 if at_goal else -0.01
        done = at_goal or self._t >= self.max_steps
        return self._obs(), reward, bool(done), {}

    def optimal_return(self) -> float:
        steps = 2 * (self.n - 1)
        return 1.0 - 0.01 * (steps - 1)
