"""Action-selection policies — `org.deeplearning4j.rl4j.policy` role
(EpsGreedy, Policy, BoltzmannQ)."""

from __future__ import annotations

import numpy as np


class GreedyPolicy:
    def select(self, q_values: np.ndarray, rng, step: int) -> int:
        return int(np.argmax(q_values))


class EpsilonGreedyPolicy:
    """Linearly annealed epsilon-greedy (the EpsGreedy role)."""

    def __init__(self, eps_start: float = 1.0, eps_end: float = 0.05,
                 anneal_steps: int = 5000):
        self.eps_start = eps_start
        self.eps_end = eps_end
        self.anneal_steps = max(1, anneal_steps)

    def epsilon(self, step: int) -> float:
        frac = min(1.0, step / self.anneal_steps)
        return self.eps_start + frac * (self.eps_end - self.eps_start)

    def select(self, q_values: np.ndarray, rng, step: int) -> int:
        if rng.random() < self.epsilon(step):
            return int(rng.integers(0, q_values.shape[-1]))
        return int(np.argmax(q_values))


class BoltzmannPolicy:
    def __init__(self, temperature: float = 1.0):
        self.temperature = temperature

    def select(self, q_values: np.ndarray, rng, step: int) -> int:
        z = q_values / max(self.temperature, 1e-8)
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(rng.choice(len(p), p=p))
