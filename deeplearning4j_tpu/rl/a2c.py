"""Advantage actor-critic — the A3C/A2C role
(`org.deeplearning4j.rl4j.learning.async.a3c`).

Synchronous single-worker A2C (the reference's async-across-JVM-threads
design is an artifact of op-at-a-time execution; with a compiled update
step, batching n-step rollouts into one program is strictly better on
TPU).  Shared torso, policy + value heads, n-step returns, entropy bonus,
one jitted update per rollout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.rl.dqn import _build_torso, _torso_apply
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.runtime.rng import SeedStream


class A2C:
    def __init__(
        self,
        obs_dim: int,
        n_actions: int,
        hidden: tuple[int, ...] = (64,),
        gamma: float = 0.99,
        lr: float = 7e-4,
        rollout_steps: int = 32,
        value_coef: float = 0.5,
        entropy_coef: float = 0.01,
        seed: int = 0,
    ):
        self.obs_dim, self.n_actions = obs_dim, n_actions
        self.gamma = gamma
        self.rollout_steps = rollout_steps
        self.value_coef = value_coef
        self.entropy_coef = entropy_coef
        self._np_rng = np.random.default_rng(seed)

        stream = SeedStream(seed)
        self.layers, torso = _build_torso(obs_dim, hidden, stream.key("torso"))
        d = hidden[-1] if hidden else obs_dim
        kp, kv = jax.random.split(stream.key("heads"))
        self.params = {
            "torso": torso,
            "pi": {"W": jax.random.normal(kp, (d, n_actions)) * 0.01,
                   "b": jnp.zeros((n_actions,))},
            "v": {"W": jax.random.normal(kv, (d, 1)) * (1 / np.sqrt(d)),
                  "b": jnp.zeros((1,))},
        }
        self._tx = optax.adam(lr)
        self.opt_state = self._tx.init(self.params)
        self._fwd = jax.jit(self._forward)
        self._update = self._make_update()

    def _forward(self, params, obs):
        h = _torso_apply(self.layers, params["torso"], obs)
        logits = h @ params["pi"]["W"] + params["pi"]["b"]
        value = (h @ params["v"]["W"] + params["v"]["b"])[..., 0]
        return logits, value

    def _make_update(self):
        @jax.jit
        def update(params, opt_state, obs, actions, returns):
            def loss_fn(p):
                logits, values = self._forward(p, obs)
                logp = jax.nn.log_softmax(logits)
                picked = jnp.take_along_axis(
                    logp, actions[:, None].astype(jnp.int32), axis=-1
                )[:, 0]
                adv = jax.lax.stop_gradient(returns - values)
                policy_loss = -jnp.mean(picked * adv)
                value_loss = jnp.mean((returns - values) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp) * logp, axis=-1)
                )
                return (
                    policy_loss
                    + self.value_coef * value_loss
                    - self.entropy_coef * entropy
                )

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return update

    def act(self, obs: np.ndarray) -> int:
        logits, _ = self._fwd(self.params, obs[None])
        p = np.asarray(jax.nn.softmax(logits))[0]
        return int(self._np_rng.choice(self.n_actions, p=p))

    def play(self, obs: np.ndarray) -> int:
        logits, _ = self._fwd(self.params, obs[None])
        return int(np.argmax(np.asarray(logits)[0]))

    def train(self, mdp: MDP, total_steps: int = 20000) -> list[float]:
        """Returns completed-episode returns in order of completion."""
        history: list[float] = []
        obs = mdp.reset()
        ep_return = 0.0
        steps_done = 0
        while steps_done < total_steps:
            obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
            for _ in range(self.rollout_steps):
                action = self.act(obs)
                next_obs, reward, done, _ = mdp.step(action)
                obs_buf.append(obs)
                act_buf.append(action)
                rew_buf.append(reward)
                done_buf.append(done)
                ep_return += reward
                steps_done += 1
                if done:
                    history.append(ep_return)
                    ep_return = 0.0
                    obs = mdp.reset()
                else:
                    obs = next_obs
            # n-step returns bootstrapped from the value head
            _, bootstrap = self._fwd(self.params, obs[None])
            ret = float(bootstrap[0])
            returns = np.zeros(len(rew_buf), np.float32)
            for i in reversed(range(len(rew_buf))):
                ret = rew_buf[i] + self.gamma * ret * (1.0 - float(done_buf[i]))
                returns[i] = ret
            self.params, self.opt_state, _ = self._update(
                self.params, self.opt_state,
                np.asarray(obs_buf, np.float32),
                np.asarray(act_buf, np.int32),
                returns,
            )
        return history
