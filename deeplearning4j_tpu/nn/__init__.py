"""Neural-network framework: config DSL, layers, models, updaters, losses.

The DL4J-proper role (SURVEY.md §1 L4): `NeuralNetConfiguration`-style
builder DSL producing JSON-serializable config trees; layer implementations;
SequentialModel (MultiLayerNetwork role) and ComputationGraph models whose
fit() compiles the whole step to one XLA computation.
"""

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.updaters import (
    AdaDelta,
    AdaGrad,
    AdaMax,
    Adam,
    AdamW,
    AmsGrad,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
    Updater,
)
from deeplearning4j_tpu.nn.weights import WeightInit

__all__ = [
    "Activation",
    "Loss",
    "WeightInit",
    "Updater",
    "Adam",
    "AdamW",
    "Sgd",
    "Nesterovs",
    "RmsProp",
    "AdaGrad",
    "AdaDelta",
    "AdaMax",
    "Nadam",
    "AmsGrad",
    "NoOp",
]
