"""Loss functions — the `org.nd4j.linalg.lossfunctions.LossFunctions` role.

Conventions: predictions enter PRE-activation for the fused softmax/sigmoid
losses (MCXENT, XENT) — the output layer declares its activation and the
loss fuses it for numerical stability, same as the reference fuses
softmax+MCXENT.  Per-example masks (variable-length sequence support,
SURVEY.md §5.7) multiply per-element losses before reduction; reduction is
mean over unmasked elements.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp


class Loss(str, enum.Enum):
    MCXENT = "mcxent"                    # softmax cross-entropy, integer or one-hot labels
    NEGATIVELOGLIKELIHOOD = "nll"        # alias of MCXENT in the reference
    XENT = "xent"                        # sigmoid binary cross-entropy
    MSE = "mse"
    MAE = "l1"
    L2 = "l2"                            # sum-of-squares (no 1/n): reference semantics
    SPARSE_MCXENT = "sparse_mcxent"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    HUBER = "huber"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    KL_DIVERGENCE = "kld"
    MAPE = "mape"                        # mean absolute percentage error
    MSLE = "msle"                        # mean squared logarithmic error
    WASSERSTEIN = "wasserstein"          # critic loss (labels +-1)
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_xent"

    def __call__(self, preds, labels, mask=None):
        return compute(self, preds, labels, mask)


# Accepted user-facing spellings beyond value/NAME (Keras-style included);
# consumed by the config layer's string→enum coercion.
Loss._ALIASES_ = {
    "categorical_crossentropy": "mcxent",
    "softmax_cross_entropy": "mcxent",
    "sparse_categorical_crossentropy": "sparse_mcxent",
    "binary_crossentropy": "xent",
    "negativeloglikelihood": "nll",
    "mean_squared_error": "mse",
    "mean_absolute_error": "l1",
    "mae": "l1",
    "kl_divergence": "kld",
    "kullback_leibler_divergence": "kld",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
}


def _masked_mean(per_elem: jax.Array, mask) -> jax.Array:
    if mask is None:
        return jnp.mean(per_elem)
    mask = jnp.broadcast_to(mask, per_elem.shape).astype(per_elem.dtype)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(per_elem * mask) / denom


FUSED_ACTIVATION_LOSSES = (
    Loss.MCXENT,
    Loss.NEGATIVELOGLIKELIHOOD,
    Loss.SPARSE_MCXENT,
    Loss.XENT,
)


def compute(
    loss: Loss, preds: jax.Array, labels: jax.Array, mask=None, from_logits: bool = True
) -> jax.Array:
    """Scalar loss.

    For the fused-activation losses (MCXENT/XENT family), `preds` are
    pre-activation logits when from_logits=True (the numerically-stable
    fused path), or already-activated probabilities when from_logits=False
    (used when the output layer declared a non-standard activation).
    Other losses always receive activated predictions.

    `mask` broadcasts against the per-example loss (shape preds.shape[:-1])
    for categorical losses, or against preds for elementwise losses.
    """
    f32 = jnp.float32
    preds = preds.astype(f32)
    if loss in (Loss.MCXENT, Loss.NEGATIVELOGLIKELIHOOD, Loss.SPARSE_MCXENT):
        if from_logits:
            logp = jax.nn.log_softmax(preds, axis=-1)
        else:
            logp = jnp.log(jnp.maximum(preds, 1e-12))
        if labels.ndim == preds.ndim - 1 or loss is Loss.SPARSE_MCXENT:
            labels_int = labels.astype(jnp.int32)
            if labels_int.ndim == preds.ndim:      # one-hot passed to sparse
                labels_int = jnp.argmax(labels_int, axis=-1)
            nll = -jnp.take_along_axis(logp, labels_int[..., None], axis=-1)[..., 0]
        else:
            nll = -jnp.sum(labels.astype(f32) * logp, axis=-1)
        return _masked_mean(nll, mask)
    if loss is Loss.XENT:
        labels = labels.astype(f32)
        if from_logits:
            per = jnp.maximum(preds, 0) - preds * labels + jnp.log1p(jnp.exp(-jnp.abs(preds)))
        else:
            p = jnp.clip(preds, 1e-7, 1 - 1e-7)
            per = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
        per = jnp.sum(per, axis=-1)
        return _masked_mean(per, mask)
    labels = labels.astype(f32)
    if loss is Loss.MSE:
        return _masked_mean(jnp.mean((preds - labels) ** 2, axis=-1), mask)
    if loss is Loss.MAE:
        return _masked_mean(jnp.mean(jnp.abs(preds - labels), axis=-1), mask)
    if loss is Loss.L2:
        return _masked_mean(jnp.sum((preds - labels) ** 2, axis=-1), mask)
    if loss is Loss.HINGE:
        # labels in {-1, +1} (or {0,1} → remapped)
        y = jnp.where(labels > 0, 1.0, -1.0)
        per = jnp.mean(jnp.maximum(0.0, 1.0 - y * preds), axis=-1)
        return _masked_mean(per, mask)
    if loss is Loss.SQUARED_HINGE:
        y = jnp.where(labels > 0, 1.0, -1.0)
        per = jnp.mean(jnp.maximum(0.0, 1.0 - y * preds) ** 2, axis=-1)
        return _masked_mean(per, mask)
    if loss is Loss.HUBER:
        d = preds - labels
        a = jnp.abs(d)
        per = jnp.mean(jnp.where(a <= 1.0, 0.5 * d * d, a - 0.5), axis=-1)
        return _masked_mean(per, mask)
    if loss is Loss.POISSON:
        per = jnp.mean(preds - labels * jnp.log(jnp.maximum(preds, 1e-12)), axis=-1)
        return _masked_mean(per, mask)
    if loss is Loss.COSINE_PROXIMITY:
        pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-12)
        ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), 1e-12)
        return _masked_mean(-jnp.sum(pn * ln, axis=-1), mask)
    if loss is Loss.KL_DIVERGENCE:
        p = jnp.maximum(labels, 1e-12)
        q = jnp.maximum(preds, 1e-12)
        return _masked_mean(jnp.sum(p * (jnp.log(p) - jnp.log(q)), axis=-1), mask)
    if loss is Loss.MAPE:
        per = jnp.mean(
            100.0 * jnp.abs((labels - preds) /
                            jnp.maximum(jnp.abs(labels), 1e-7)),
            axis=-1,
        )
        return _masked_mean(per, mask)
    if loss is Loss.MSLE:
        per = jnp.mean(
            (jnp.log1p(jnp.maximum(labels, 0.0))
             - jnp.log1p(jnp.maximum(preds, 0.0))) ** 2,
            axis=-1,
        )
        return _masked_mean(per, mask)
    if loss is Loss.WASSERSTEIN:
        # critic objective: labels are +1 (real) / -1 (generated)
        return _masked_mean(jnp.mean(-labels * preds, axis=-1), mask)
    if loss is Loss.RECONSTRUCTION_CROSSENTROPY:
        p = jnp.clip(preds, 1e-7, 1 - 1e-7)
        per = -jnp.sum(
            labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p), axis=-1
        )
        return _masked_mean(per, mask)
    raise ValueError(f"unhandled loss {loss}")
