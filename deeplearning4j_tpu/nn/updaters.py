"""Updaters (optimizers) — the `org.nd4j.linalg.learning.config.IUpdater` role.

Each updater is a JSON-serializable dataclass config that lowers to an
optax GradientTransformation.  Unlike the reference — where updater kernels
run as separate libnd4j ops per parameter block (SURVEY.md §3.1) — the
transformation is traced into the same XLA computation as forward+backward,
so Adam's moment updates fuse with the gradient producers.

Updater STATE (moments etc.) is a pytree checkpointed alongside params,
matching the reference's updaterState.bin (SURVEY.md §5.4).
"""

from __future__ import annotations

import dataclasses

import optax

from deeplearning4j_tpu.nn.schedules import ScheduleLike, as_schedule
from deeplearning4j_tpu.utils import serde


@dataclasses.dataclass(frozen=True)
class Updater:
    """Base updater config. learning_rate may be a float or a Schedule."""

    learning_rate: ScheduleLike = 1e-3

    def _lr(self, steps_per_epoch: int):
        return as_schedule(self.learning_rate).to_fn(steps_per_epoch)

    def to_optax(self, steps_per_epoch: int = 1) -> optax.GradientTransformation:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    def to_optax(self, steps_per_epoch: int = 1):
        return optax.sgd(self._lr(steps_per_epoch))


@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    learning_rate: ScheduleLike = 0.1
    momentum: float = 0.9

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.sgd(self._lr(steps_per_epoch), momentum=self.momentum, nesterov=True)


@dataclasses.dataclass(frozen=True)
class Momentum(Updater):
    learning_rate: ScheduleLike = 0.1
    momentum: float = 0.9

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.sgd(self._lr(steps_per_epoch), momentum=self.momentum)


@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.adam(self._lr(steps_per_epoch), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdamW(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.01

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.adamw(
            self._lr(steps_per_epoch),
            b1=self.beta1,
            b2=self.beta2,
            eps=self.epsilon,
            weight_decay=self.weight_decay,
        )


@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.adamax(self._lr(steps_per_epoch), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.nadam(self._lr(steps_per_epoch), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AmsGrad(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.amsgrad(self._lr(steps_per_epoch), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    epsilon: float = 1e-6

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.adagrad(self._lr(steps_per_epoch), eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self, steps_per_epoch: int = 1):
        # AdaDelta in the reference ignores the learning rate.
        return optax.adadelta(rho=self.rho, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.rmsprop(self._lr(steps_per_epoch), decay=self.decay, eps=self.epsilon)


@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    """Frozen parameters (the reference's NoOp updater / FrozenLayer)."""

    def to_optax(self, steps_per_epoch: int = 1):
        return optax.set_to_zero()


for _cls in (Sgd, Nesterovs, Momentum, Adam, AdamW, AdaMax, Nadam, AmsGrad,
             AdaGrad, AdaDelta, RmsProp, NoOp):
    serde.register(_cls)


def with_gradient_clipping(
    tx: optax.GradientTransformation,
    clip_value: float | None = None,
    clip_norm: float | None = None,
) -> optax.GradientTransformation:
    """GradientNormalization.{ClipElementWiseAbsoluteValue,ClipL2PerLayer} role."""
    chain = []
    if clip_value is not None:
        chain.append(optax.clip(clip_value))
    if clip_norm is not None:
        chain.append(optax.clip_by_global_norm(clip_norm))
    chain.append(tx)
    return optax.chain(*chain)
