"""Activation functions — the `org.nd4j.linalg.activations.Activation` enum role.

The reference enumerates activations as op classes dispatched per-call
through the executioner; here each is a pure jnp function fused by XLA into
the surrounding computation (elementwise ops ride along with the matmul's
HBM traffic for free — SURVEY.md §2.1 TPU mapping note).
"""

from __future__ import annotations

import enum
from collections.abc import Callable

import jax
import jax.numpy as jnp


class Activation(str, enum.Enum):
    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SILU = "silu"            # a.k.a. swish
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    TANH = "tanh"
    HARDTANH = "hardtanh"
    SOFTMAX = "softmax"
    LOGSOFTMAX = "logsoftmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    CUBE = "cube"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    THRESHOLDEDRELU = "thresholdedrelu"
    MISH = "mish"

    def fn(self) -> Callable[[jax.Array], jax.Array]:
        return _TABLE[self]

    def __call__(self, x: jax.Array) -> jax.Array:
        return _TABLE[self](x)


def _rational_tanh(x):
    # DL4J's rationaltanh: 1.7159 * tanh-approx via rational polynomial.
    a = jnp.abs(x)
    approx = jnp.clip(x * (1.0 + a / 2 + a * a / 16), -1.0, 1.0)
    return 1.7159 * approx


_TABLE: dict[Activation, Callable] = {
    Activation.IDENTITY: lambda x: x,
    Activation.RELU: jax.nn.relu,
    Activation.RELU6: jax.nn.relu6,
    Activation.LEAKYRELU: lambda x: jax.nn.leaky_relu(x, 0.01),
    Activation.ELU: jax.nn.elu,
    Activation.SELU: jax.nn.selu,
    Activation.GELU: jax.nn.gelu,
    Activation.SILU: jax.nn.silu,
    Activation.SIGMOID: jax.nn.sigmoid,
    Activation.HARDSIGMOID: jax.nn.hard_sigmoid,
    Activation.TANH: jnp.tanh,
    Activation.HARDTANH: lambda x: jnp.clip(x, -1.0, 1.0),
    Activation.SOFTMAX: lambda x: jax.nn.softmax(x, axis=-1),
    Activation.LOGSOFTMAX: lambda x: jax.nn.log_softmax(x, axis=-1),
    Activation.SOFTPLUS: jax.nn.softplus,
    Activation.SOFTSIGN: jax.nn.soft_sign,
    Activation.CUBE: lambda x: x * x * x,
    Activation.RATIONALTANH: _rational_tanh,
    Activation.RECTIFIEDTANH: lambda x: jnp.maximum(jnp.tanh(x), 0.0),
    Activation.THRESHOLDEDRELU: lambda x: jnp.where(x > 1.0, x, 0.0),
    Activation.MISH: lambda x: x * jnp.tanh(jax.nn.softplus(x)),
}
