"""Object-detection output layer — the `Yolo2OutputLayer` role.

Reference: `org.deeplearning4j.nn.layers.objdetect.Yolo2OutputLayer` (used
by the zoo's TinyYOLO/YOLO2 models).  The YOLOv2 loss over an anchor-box
grid: responsible-anchor coordinate regression, objectness confidence with
a no-object down-weight, and per-cell class cross-entropy.

TPU-native differences from the reference:
- feature maps stay NHWC; predictions reshape to (B, H, W, A, 5+C) in one
  XLA reshape (the reference permutes to channels-first for cuDNN);
- ground-truth assignment (best-IoU anchor per box) runs host-side in the
  data pipeline (`build_targets`), so the compiled loss is pure dense math —
  no data-dependent control flow under jit;
- the loss is fully vectorized: masks instead of per-box loops.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig
from deeplearning4j_tpu.utils import serde


@serde.register
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(LayerConfig):
    """YOLOv2 detection head over a conv feature map.

    Input: (B, H, W, A*(5+C)) conv activations.  Raw per-anchor layout
    [tx, ty, tw, th, conf, class-logits...].  Labels: the dense target grid
    produced by `build_targets`, shape (B, H, W, A, 5+C) with layout
    [obj, x, y, log-w, log-h, class-onehot...] (x/y offsets within the
    cell, w/h in log-ratio to the anchor).
    """

    anchors: Tuple[Tuple[float, float], ...] = ()   # (w, h) in grid units
    num_classes: int = 0
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    @property
    def num_anchors(self) -> int:
        return len(self.anchors)

    def _split(self, raw):
        b, h, w, _ = raw.shape
        a, c = self.num_anchors, self.num_classes
        g = raw.reshape(b, h, w, a, 5 + c)
        return g[..., 0], g[..., 1], g[..., 2], g[..., 3], g[..., 4], g[..., 5:]

    def output_type(self, itype: InputType) -> InputType:
        h, w, c = itype.shape
        need = self.num_anchors * (5 + self.num_classes)
        if c != need:
            raise ValueError(
                f"Yolo2OutputLayer needs {need} input channels "
                f"({self.num_anchors} anchors x (5+{self.num_classes})), got {c}"
            )
        return itype

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state   # raw grid; loss/decode interpret it

    # -- loss (called by the model's compiled step via the custom-loss hook)
    def compute_loss(self, preds, labels, mask=None):
        preds = preds.astype(jnp.float32)
        labels = labels.astype(jnp.float32)
        tx, ty, tw, th, tconf, tcls = self._split(preds.reshape(preds.shape[0], preds.shape[1], preds.shape[2], -1))
        obj = labels[..., 0]                      # (B,H,W,A)
        gx, gy, gw, gh = labels[..., 1], labels[..., 2], labels[..., 3], labels[..., 4]
        gcls = labels[..., 5:]

        px, py = jax.nn.sigmoid(tx), jax.nn.sigmoid(ty)
        pconf = jax.nn.sigmoid(tconf)

        coord = obj * (
            jnp.square(px - gx) + jnp.square(py - gy)
            + jnp.square(tw - gw) + jnp.square(th - gh)
        )
        conf = obj * jnp.square(pconf - 1.0) + self.lambda_noobj * (1.0 - obj) * jnp.square(pconf)
        logp = jax.nn.log_softmax(tcls, axis=-1)
        cls = obj * (-jnp.sum(gcls * logp, axis=-1))

        per_image = jnp.sum(
            self.lambda_coord * coord + conf + cls, axis=(1, 2, 3)
        )
        if mask is not None:
            m = mask.reshape(-1).astype(jnp.float32)
            # normalize by the mask sum, matching losses._masked_mean —
            # otherwise padded batches silently rescale the gradients
            return jnp.sum(per_image * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(per_image)

    # -- inference decode ------------------------------------------------
    def decode(self, preds) -> dict:
        """Raw grid → boxes in grid units.

        Returns dict of arrays: `xy` (B,H,W,A,2) box centers, `wh` box sizes,
        `conf` (B,H,W,A) objectness, `class_probs` (B,H,W,A,C)
        (the reference's YoloUtils.getPredictedObjects role, minus NMS —
        see `non_max_suppression`).
        """
        preds = jnp.asarray(preds, jnp.float32)
        tx, ty, tw, th, tconf, tcls = self._split(preds)
        h, w = preds.shape[1], preds.shape[2]
        cx = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, w, 1)
        cy = jnp.arange(h, dtype=jnp.float32).reshape(1, h, 1, 1)
        anchors = jnp.asarray(self.anchors, jnp.float32)  # (A, 2)
        x = jax.nn.sigmoid(tx) + cx
        y = jax.nn.sigmoid(ty) + cy
        bw = jnp.exp(tw) * anchors[:, 0]
        bh = jnp.exp(th) * anchors[:, 1]
        return {
            "xy": jnp.stack([x, y], axis=-1),
            "wh": jnp.stack([bw, bh], axis=-1),
            "conf": jax.nn.sigmoid(tconf),
            "class_probs": jax.nn.softmax(tcls, axis=-1),
        }


def _iou_wh(wh1, wh2) -> float:
    """IoU of two boxes sharing a center (anchor matching uses w/h only)."""
    inter = min(wh1[0], wh2[0]) * min(wh1[1], wh2[1])
    union = wh1[0] * wh1[1] + wh2[0] * wh2[1] - inter
    return inter / union if union > 0 else 0.0


def build_targets(
    boxes_per_image: Sequence[Sequence],
    grid_h: int,
    grid_w: int,
    anchors: Sequence[Tuple[float, float]],
    num_classes: int,
) -> np.ndarray:
    """Host-side dense target grid builder.

    boxes_per_image: per image, a list of (class_idx, cx, cy, w, h) in
    grid units (cx/cy in [0, grid), w/h > 0).  Each box is assigned to its
    cell and the best-IoU anchor; target layout matches Yolo2OutputLayer.
    """
    a, c = len(anchors), num_classes
    out = np.zeros((len(boxes_per_image), grid_h, grid_w, a, 5 + c), np.float32)
    for i, boxes in enumerate(boxes_per_image):
        for cls_idx, cx, cy, w, h in boxes:
            col = min(int(cx), grid_w - 1)
            row = min(int(cy), grid_h - 1)
            best = max(range(a), key=lambda k: _iou_wh((w, h), anchors[k]))
            out[i, row, col, best, 0] = 1.0
            out[i, row, col, best, 1] = cx - col          # offset within cell
            out[i, row, col, best, 2] = cy - row
            out[i, row, col, best, 3] = np.log(max(w, 1e-6) / anchors[best][0])
            out[i, row, col, best, 4] = np.log(max(h, 1e-6) / anchors[best][1])
            out[i, row, col, best, 5 + int(cls_idx)] = 1.0
    return out


def non_max_suppression(
    boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.45,
    score_threshold: float = 0.3, max_out: int = 50,
):
    """Greedy NMS over decoded boxes (host-side post-processing).

    boxes: (N, 4) as (cx, cy, w, h); scores: (N,).  Returns kept indices.
    """
    keep = []
    order = np.argsort(-scores)
    order = order[scores[order] >= score_threshold]
    x1 = boxes[:, 0] - boxes[:, 2] / 2
    y1 = boxes[:, 1] - boxes[:, 3] / 2
    x2 = boxes[:, 0] + boxes[:, 2] / 2
    y2 = boxes[:, 1] + boxes[:, 3] / 2
    areas = (x2 - x1) * (y2 - y1)
    while order.size and len(keep) < max_out:
        i = order[0]
        keep.append(int(i))
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas[order[1:]] - inter, 1e-9)
        order = order[1:][iou <= iou_threshold]
    return keep


@dataclasses.dataclass(frozen=True)
class DetectedObject:
    """One detection in grid units (the reference's DetectedObject)."""

    class_index: int
    confidence: float
    center_x: float
    center_y: float
    width: float
    height: float

    def top_left(self) -> tuple[float, float]:
        return (self.center_x - self.width / 2, self.center_y - self.height / 2)

    def bottom_right(self) -> tuple[float, float]:
        return (self.center_x + self.width / 2, self.center_y + self.height / 2)


def get_predicted_objects(
    layer: "Yolo2OutputLayer",
    preds,
    *,
    score_threshold: float = 0.3,
    iou_threshold: float = 0.45,
    max_out: int = 50,
) -> list[list[DetectedObject]]:
    """Decode + threshold + NMS into DetectedObject lists, one per image
    (YoloUtils.getPredictedObjects role: the full raw-grid -> detections
    path).  Score = objectness * best class probability."""
    d = layer.decode(preds)
    xy = np.asarray(d["xy"], np.float32)
    wh = np.asarray(d["wh"], np.float32)
    conf = np.asarray(d["conf"], np.float32)
    cls_p = np.asarray(d["class_probs"], np.float32)
    out = []
    for b in range(xy.shape[0]):
        boxes = np.concatenate(
            [xy[b].reshape(-1, 2), wh[b].reshape(-1, 2)], axis=1
        )
        c = conf[b].reshape(-1)
        p = cls_p[b].reshape(-1, cls_p.shape[-1])
        best = p.argmax(axis=1)
        scores = c * p.max(axis=1)
        # PER-CLASS NMS (reference YoloUtils semantics): overlapping
        # objects of DIFFERENT classes must not suppress each other
        dets = []
        for cls_idx in np.unique(best[scores >= score_threshold]):
            sel = np.flatnonzero(best == cls_idx)
            keep = non_max_suppression(
                boxes[sel], scores[sel], iou_threshold=iou_threshold,
                score_threshold=score_threshold, max_out=max_out,
            )
            dets.extend(
                DetectedObject(
                    class_index=int(cls_idx),
                    confidence=float(scores[i]),
                    center_x=float(boxes[i, 0]),
                    center_y=float(boxes[i, 1]),
                    width=float(boxes[i, 2]),
                    height=float(boxes[i, 3]),
                )
                for i in sel[keep]
            )
        dets.sort(key=lambda d: -d.confidence)
        out.append(dets[:max_out])
    return out
