from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    Embedding,
    GlobalPooling,
    LayerConfig,
    LayerNorm,
    LocalResponseNormalization,
    OutputLayer,
    PoolingType,
    Subsampling,
    Upsampling2D,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
    NeuralNetConfiguration,
    SequentialConfiguration,
)

__all__ = [
    "InputType",
    "LayerConfig",
    "Dense",
    "Conv2D",
    "Subsampling",
    "PoolingType",
    "BatchNorm",
    "LayerNorm",
    "LocalResponseNormalization",
    "Dropout",
    "Embedding",
    "GlobalPooling",
    "ActivationLayer",
    "OutputLayer",
    "Upsampling2D",
    "ZeroPadding2D",
    "NeuralNetConfiguration",
    "SequentialConfiguration",
]
