"""NeuralNetConfiguration builder DSL — the reference's central config entry.

Mirrors the capability of
`new NeuralNetConfiguration.Builder().seed(..).updater(..).list().layer(..)
 .setInputType(..).build()` (SURVEY.md §2.2): model-level defaults flow into
layers that didn't override them; the result is a JSON-round-trippable
SequentialConfiguration with all shapes inferred.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig
from deeplearning4j_tpu.nn.updaters import Sgd, Updater
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.utils import serde


@serde.register
@dataclasses.dataclass(frozen=True)
class SequentialConfiguration:
    """The MultiLayerConfiguration role: resolved, serializable."""

    layers: tuple[LayerConfig, ...] = ()
    input_type: Optional[InputType] = None
    updater: Updater = dataclasses.field(default_factory=Sgd)
    seed: int = 0
    gradient_clip_value: Optional[float] = None
    gradient_clip_norm: Optional[float] = None
    # Cast activations to bfloat16 inside the step (params stay f32).
    # None = auto: bf16 on TPU, f32 elsewhere.
    bf16_compute: Optional[bool] = None
    # Iterations per epoch, used to lower epoch-based LR schedules
    # (ScheduleType.EPOCH role). Set via builder.steps_per_epoch().
    steps_per_epoch: int = 1
    # BackpropType role: "standard" or "tbptt" (truncated BPTT for long
    # sequences: gradients flow within tbptt_length windows; RNN carries
    # are forwarded across windows).
    backprop_type: str = "standard"
    tbptt_length: int = 0

    def to_json(self) -> str:
        return serde.dumps(self)

    @staticmethod
    def from_json(s: str) -> "SequentialConfiguration":
        cfg = serde.loads(s)
        if not isinstance(cfg, SequentialConfiguration):
            raise TypeError(f"JSON did not decode to SequentialConfiguration: {type(cfg)}")
        return cfg

    def _walk_types(self) -> tuple[list[InputType], list[bool]]:
        """Single source of truth for the type walk down the stack,
        including the implicit CNN->FF flatten (InputPreProcessor role):
        when a layer EXPECTS 'ff' but the incoming type is CNN, a reshape
        is inserted; flags[i] records it so the model applies the SAME rule
        at trace time."""
        if self.input_type is None:
            raise ValueError("configuration has no input_type; call set_input_type")
        itypes, flags = [], []
        cur = self.input_type
        for layer in self.layers:
            flat = layer.EXPECTS == "ff" and cur.kind in (
                InputType.KIND_CNN,
                InputType.KIND_CNN3D,
            )
            if flat:
                cur = InputType.feed_forward(cur.flat_size)
            flags.append(flat)
            itypes.append(cur)
            cur = layer.output_type(cur)
        return itypes, flags

    def layer_input_types(self) -> list[InputType]:
        """Input type seen by each layer (post-flatten where applicable)."""
        return self._walk_types()[0]

    def flatten_flags(self) -> list[bool]:
        """Whether an implicit flatten precedes each layer."""
        return self._walk_types()[1]

    def output_type(self) -> InputType:
        itypes = self.layer_input_types()
        return self.layers[-1].output_type(itypes[-1])


class NeuralNetConfiguration:
    """Fluent builder. Example:

        conf = (NeuralNetConfiguration.builder()
                .seed(123)
                .updater(Adam(1e-3))
                .weight_init(WeightInit.XAVIER)
                .activation(Activation.RELU)
                .l2(1e-4)
                .list()
                .layer(Conv2D(n_out=20, kernel=(5, 5)))
                .layer(Subsampling(kernel=(2, 2), stride=(2, 2)))
                .layer(Dense(n_out=500))
                .layer(OutputLayer(n_out=10, loss=Loss.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
    """

    def __init__(self):
        self._seed = 0
        self._updater: Updater = Sgd()
        self._activation: Optional[Activation] = None
        self._weight_init: Optional[WeightInit] = None
        self._l1: Optional[float] = None
        self._l2: Optional[float] = None
        self._dropout: Optional[float] = None
        self._clip_value: Optional[float] = None
        self._clip_norm: Optional[float] = None
        self._bf16: Optional[bool] = None
        self._steps_per_epoch = 1
        self._backprop_type = "standard"
        self._tbptt_length = 0
        self._layers: list[LayerConfig] = []
        self._input_type: Optional[InputType] = None

    @staticmethod
    def builder() -> "NeuralNetConfiguration":
        return NeuralNetConfiguration()

    def seed(self, s: int):
        self._seed = int(s)
        return self

    def updater(self, u: Updater):
        self._updater = u
        return self

    def activation(self, a: Activation):
        self._activation = a
        return self

    def weight_init(self, w: WeightInit):
        self._weight_init = w
        return self

    def l1(self, v: float):
        self._l1 = v
        return self

    def l2(self, v: float):
        self._l2 = v
        return self

    def dropout(self, rate: float):
        self._dropout = rate
        return self

    def gradient_clip(self, value: float | None = None, norm: float | None = None):
        self._clip_value, self._clip_norm = value, norm
        return self

    def bf16_compute(self, on: bool):
        self._bf16 = on
        return self

    def steps_per_epoch(self, n: int):
        """Iterations per epoch — required for per-epoch LR schedules."""
        self._steps_per_epoch = max(1, int(n))
        return self

    def tbptt(self, length: int):
        """Enable truncated BPTT with the given window length
        (BackpropType.TruncatedBPTT role)."""
        self._backprop_type = "tbptt"
        self._tbptt_length = int(length)
        return self

    def list(self):
        return self

    def layer(self, layer: LayerConfig):
        self._layers.append(self._fill_defaults(layer))
        return self

    def set_input_type(self, itype: InputType):
        self._input_type = itype
        return self

    def _fill_defaults(self, layer: LayerConfig) -> LayerConfig:
        updates = {}
        # The global activation default never flows into output layers: their
        # activation is resolved from the loss (softmax for MCXENT etc.);
        # a global RELU leaking in would corrupt output()/predict().
        is_output = hasattr(layer, "loss")
        if layer.activation is None and self._activation is not None and not is_output:
            updates["activation"] = self._activation
        if layer.weight_init is None and self._weight_init is not None:
            updates["weight_init"] = self._weight_init
        if layer.l1 is None and self._l1 is not None:
            updates["l1"] = self._l1
        if layer.l2 is None and self._l2 is not None:
            updates["l2"] = self._l2
        if layer.dropout_rate is None and self._dropout is not None:
            updates["dropout_rate"] = self._dropout
        if layer.name is None:
            updates["name"] = f"layer{len(self._layers)}"
        return dataclasses.replace(layer, **updates) if updates else layer

    def build(self) -> SequentialConfiguration:
        if not self._layers:
            raise ValueError("no layers configured")
        names = [l.name for l in self._layers]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"duplicate layer names {sorted(dupes)}: explicit names collide "
                "with auto-generated 'layer<N>' names or each other"
            )
        return SequentialConfiguration(
            layers=tuple(self._layers),
            input_type=self._input_type,
            updater=self._updater,
            seed=self._seed,
            gradient_clip_value=self._clip_value,
            gradient_clip_norm=self._clip_norm,
            bf16_compute=self._bf16,
            steps_per_epoch=self._steps_per_epoch,
            backprop_type=self._backprop_type,
            tbptt_length=self._tbptt_length,
        )
