"""Layer configuration dataclasses + their pure forward implementations.

The reference splits layer *config* (org.deeplearning4j.nn.conf.layers.*)
from layer *runtime* (org.deeplearning4j.nn.layers.*) because runtime
layers hold mutable INDArray state.  TPU-native there is no mutable layer
object: each config owns three pure functions —

    output_type(input_type)          static shape inference
    init(key, input_type)            -> (params pytree, state pytree)
    apply(params, state, x, ...)     -> (y, new_state)

`apply` is traced into the model's single compiled train/inference step, so
"layers" cost nothing at runtime; XLA fuses across them.  There is no
backpropGradient anywhere — jax.grad differentiates the whole step
(replacing the reference's per-layer hand-written backward passes).

Layout: NHWC / seq-major (B, T, F) — see input_type.py for why.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.quant import functional as quantf
from deeplearning4j_tpu.utils import serde

# Reserved key in a layer's returned state: an auxiliary loss the compiled
# training step adds to the objective (MoE load balancing etc.).  Aux
# entries are popped before state is carried — see models/_common.py
# pop_aux_losses.
AUX_LOSS_KEY = "__aux_loss__"


class PoolingType(str, enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


def _pair(v) -> tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _coerce_enum(v, enum_cls):
    """Accept an enum member, its value ("relu"), its NAME ("RELU"), or an
    alias from the enum's optional _ALIASES_ table."""
    if isinstance(v, enum_cls):
        return v
    s = str(v).lower()
    s = getattr(enum_cls, "_ALIASES_", {}).get(s, s)
    try:
        return enum_cls(s)
    except ValueError:
        pass
    try:
        return enum_cls[str(v).upper()]
    except KeyError:
        raise ValueError(
            f"{v!r} is not a valid {enum_cls.__name__}; "
            f"options: {[e.value for e in enum_cls]}"
        ) from None


def _dropout(x, rate: float, training: bool, rng):
    """Inverted dropout on the layer input (reference semantics: dropOut
    applies to a layer's input activations)."""
    if not training or rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class LayerConfig:
    """Base layer config.

    Fields that default to None are filled from the model-level
    NeuralNetConfiguration defaults at build time (the reference's
    global-config-with-layer-override pattern).
    """

    name: Optional[str] = None
    activation: Optional[Activation] = None
    weight_init: Optional[WeightInit] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout_rate: Optional[float] = None   # probability of dropping (NOT retain prob)
    frozen: bool = False                   # FrozenLayer role: excluded from updates

    # Which input kind apply() expects; the model auto-inserts reshapes
    # (the reference's InputPreProcessor role) when kinds mismatch.
    EXPECTS = "any"
    HAS_PARAMS = True
    # Layers that consume the (B, T) sequence mask declare this; the model
    # threads features_mask into their apply(mask=...) kwarg.
    ACCEPTS_MASK = False

    def __post_init__(self):
        # User-facing coercions: plain strings are accepted everywhere the
        # reference accepts an enum (Activation.RELU vs "relu"), and padding
        # is case-insensitive — "SAME" must not silently diverge from "same"
        # in output_type's shape math.
        if self.activation is not None:
            object.__setattr__(self, "activation", _coerce_enum(self.activation, Activation))
        if self.weight_init is not None:
            object.__setattr__(self, "weight_init", _coerce_enum(self.weight_init, WeightInit))
        pad = getattr(self, "padding", None)
        if isinstance(pad, str):
            object.__setattr__(self, "padding", pad.lower())
        loss = getattr(self, "loss", None)
        if loss is not None:
            object.__setattr__(self, "loss", _coerce_enum(loss, Loss))
        pooling = getattr(self, "pooling", None)
        if pooling is not None:
            object.__setattr__(self, "pooling", _coerce_enum(pooling, PoolingType))

    def output_type(self, itype: InputType) -> InputType:
        return itype

    def init(self, key: jax.Array, itype: InputType) -> tuple[dict, dict]:
        return {}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        raise NotImplementedError

    # regularization hook: which param names are penalized by l1/l2
    REGULARIZED = ("W",)

    def regularizable_params(self, lp: dict) -> list:
        """Arrays the l1/l2 penalty applies to (wrappers with nested param
        dicts override this)."""
        return [lp[p] for p in self.REGULARIZED if p in lp]

    def regularization_terms(self, lp: dict) -> list:
        """(l1, l2, array) triples — wrappers override to surface their
        inner layer's own coefficients."""
        l1, l2 = self.l1 or 0.0, self.l2 or 0.0
        if not l1 and not l2:
            return []
        return [(l1, l2, w) for w in self.regularizable_params(lp)]

    def _act(self, default=Activation.IDENTITY) -> Activation:
        return self.activation if self.activation is not None else default

    def _winit(self, default=WeightInit.XAVIER) -> WeightInit:
        return self.weight_init if self.weight_init is not None else default


# ---------------------------------------------------------------------------
# Feed-forward layers
# ---------------------------------------------------------------------------

@serde.register
@dataclasses.dataclass(frozen=True)
class Dense(LayerConfig):
    """Fully connected layer (DenseLayer role). nIn is inferred."""

    n_out: int = 0
    has_bias: bool = True

    EXPECTS = "ff"

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        n_in = itype.size
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        # quantf.matmul: `x @ W` for f32 weights, the fused
        # dequant-matmul (int8 weights, f32 accumulate) after quantize()
        y = quantf.matmul(x, params["W"])
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act()(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class OutputLayer(Dense):
    """Dense + declared loss (the reference's OutputLayer).  apply() returns
    PRE-activation logits; the model fuses activation into the loss for
    training and applies it for output()/predict."""

    loss: Loss = Loss.MCXENT

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        y = quantf.matmul(x, params["W"])
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state   # logits; activation fused into loss / applied at output()


@serde.register
@dataclasses.dataclass(frozen=True)
class LossLayer(LayerConfig):
    """Parameterless output: attaches a loss to whatever precedes it."""

    loss: Loss = Loss.MCXENT
    HAS_PARAMS = False
    REGULARIZED = ()

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


@serde.register
@dataclasses.dataclass(frozen=True)
class ActivationLayer(LayerConfig):
    HAS_PARAMS = False
    REGULARIZED = ()
    # slope/scale override for the parameterized activations (Keras
    # LeakyReLU carries alpha=0.3 by default vs this enum's 0.01; ELU
    # carries a scale) — None keeps the enum's canonical constant
    alpha: Optional[float] = None

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.alpha is not None:
            if self.activation == Activation.LEAKYRELU:
                return jax.nn.leaky_relu(x, self.alpha), state
            if self.activation == Activation.ELU:
                return jax.nn.elu(x, self.alpha), state
        return self._act()(x), state


@serde.register
@dataclasses.dataclass(frozen=True)
class ScaleShift(LayerConfig):
    """Fixed elementwise `x * scale + shift` (the ScaleVertex role, as a
    sequential layer).  Primary use: device-side image normalization for
    the uint8 ETL wire path — `ScaleShift(scale=1/255.)` first in the
    stack replaces a host-side ImagePreProcessingScaler, so batches cross
    the host->device link as bytes and the scaling fuses into the jitted
    step (zero extra HBM traffic; XLA folds it into the following conv's
    input read)."""

    scale: float = 1.0
    shift: float = 0.0
    HAS_PARAMS = False
    REGULARIZED = ()

    def apply(self, params, state, x, *, training=False, rng=None):
        y = x * jnp.asarray(self.scale, x.dtype) + jnp.asarray(
            self.shift, x.dtype)
        return self._act()(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Dropout(LayerConfig):
    """Standalone dropout layer (DropoutLayer role)."""

    rate: float = 0.5
    HAS_PARAMS = False
    REGULARIZED = ()

    def apply(self, params, state, x, *, training=False, rng=None):
        return _dropout(x, self.rate, training, rng), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Embedding(LayerConfig):
    """EmbeddingLayer/EmbeddingSequenceLayer role: int ids -> vectors.

    Accepts (B,) -> (B, n_out) [ff] or (B, T) -> (B, T, n_out) [rnn].
    """

    n_in: int = 0
    n_out: int = 0
    EXPECTS = "any"
    REGULARIZED = ("W",)

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == InputType.KIND_RNN:
            return InputType.recurrent(self.n_out, itype.shape[0])
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        n_in = self.n_in
        if n_in <= 0:
            raise ValueError("Embedding.n_in (vocab size) must be set explicitly")
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        return {"W": w}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32)
        # quantized tables gather int8 ROWS and dequantize only those —
        # the lookup touches 1 byte/weight instead of 4
        y = quantf.embedding_lookup(params["W"], ids)
        return self._act()(y), state


# ---------------------------------------------------------------------------
# Convolutional layers (NHWC)
# ---------------------------------------------------------------------------

@serde.register
@dataclasses.dataclass(frozen=True)
class Conv2D(LayerConfig):
    """2D convolution (ConvolutionLayer role).

    The reference lowers conv to im2col+gemm in libnd4j or cuDNN
    (SURVEY.md §3.1); here it is one lax.conv_general_dilated that XLA maps
    directly onto the MXU.  Kernel layout HWIO, feature-map layout NHWC.
    """

    n_out: int = 0
    kernel: tuple[int, int] = (3, 3)
    stride: tuple[int, int] = (1, 1)
    padding: str = "valid"             # "same" | "valid"
    dilation: tuple[int, int] = (1, 1)
    groups: int = 1                    # n_in groups => depthwise
    has_bias: bool = True

    EXPECTS = "cnn"

    def _out_hw(self, h: int, w: int) -> tuple[int, int]:
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
        if self.padding == "same":
            return -(-h // sh), -(-w // sw)
        return (h - ekh) // sh + 1, (w - ekw) // sw + 1

    def output_type(self, itype: InputType) -> InputType:
        h, w, _ = itype.shape
        oh, ow = self._out_hw(h, w)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, itype):
        c_in = itype.channels
        kh, kw = _pair(self.kernel)
        if c_in % self.groups:
            raise ValueError(f"channels {c_in} not divisible by groups {self.groups}")
        shape = (kh, kw, c_in // self.groups, self.n_out)
        fan_in = kh * kw * (c_in // self.groups)
        fan_out = kh * kw * self.n_out // self.groups
        w = self._winit(WeightInit.RELU).init(key, shape, fan_in=fan_in, fan_out=fan_out)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        # conv_weight: plain dtype cast, or dequantized int8 kernel (the
        # cast+scale fuse into the conv's weight read)
        w = quantf.conv_weight(params["W"], x.dtype)
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=_pair(self.stride),
            padding=self.padding.upper(),
            rhs_dilation=_pair(self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.groups,
        ).astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act(Activation.IDENTITY)(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class SeparableConv2D(LayerConfig):
    """Depthwise + pointwise conv (SeparableConvolution2D role)."""

    n_out: int = 0
    kernel: tuple[int, int] = (3, 3)
    stride: tuple[int, int] = (1, 1)
    padding: str = "valid"
    depth_multiplier: int = 1
    has_bias: bool = True

    EXPECTS = "cnn"

    def output_type(self, itype: InputType) -> InputType:
        h, w, _ = itype.shape
        dummy = Conv2D(n_out=self.n_out, kernel=self.kernel, stride=self.stride, padding=self.padding)
        oh, ow = dummy._out_hw(h, w)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, itype):
        c_in = itype.channels
        kh, kw = _pair(self.kernel)
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.RELU)
        depth = wi.init(k1, (kh, kw, 1, c_in * self.depth_multiplier), fan_in=kh * kw, fan_out=self.depth_multiplier)
        point = wi.init(
            k2,
            (1, 1, c_in * self.depth_multiplier, self.n_out),
            fan_in=c_in * self.depth_multiplier,
            fan_out=self.n_out,
        )
        params = {"depthW": depth, "pointW": point}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    REGULARIZED = ("depthW", "pointW")

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        c_in = x.shape[-1]
        y = lax.conv_general_dilated(
            x,
            quantf.conv_weight(params["depthW"], x.dtype),
            window_strides=_pair(self.stride),
            padding=self.padding.upper(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in,
        ).astype(x.dtype)
        y = lax.conv_general_dilated(
            y,
            quantf.conv_weight(params["pointW"], x.dtype),
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act()(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Deconv2D(LayerConfig):
    """Transposed convolution (Deconvolution2D role)."""

    n_out: int = 0
    kernel: tuple[int, int] = (2, 2)
    stride: tuple[int, int] = (2, 2)
    padding: str = "valid"
    has_bias: bool = True

    EXPECTS = "cnn"

    def output_type(self, itype: InputType) -> InputType:
        h, w, _ = itype.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        if self.padding == "same":
            oh, ow = h * sh, w * sw
        else:
            # matches lax.conv_transpose VALID: h*s + max(k-s, 0)
            oh, ow = h * sh + max(kh - sh, 0), w * sw + max(kw - sw, 0)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, itype):
        c_in = itype.channels
        kh, kw = _pair(self.kernel)
        w = self._winit(WeightInit.RELU).init(
            key, (kh, kw, c_in, self.n_out), fan_in=kh * kw * c_in, fan_out=kh * kw * self.n_out
        )
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        y = lax.conv_transpose(
            x,
            params["W"].astype(x.dtype),
            strides=_pair(self.stride),
            padding=self.padding.upper(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act()(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Subsampling(LayerConfig):
    """Pooling layer (SubsamplingLayer role)."""

    pooling: PoolingType = PoolingType.MAX
    kernel: tuple[int, int] = (2, 2)
    stride: tuple[int, int] = (2, 2)
    padding: str = "valid"
    pnorm: int = 2

    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def output_type(self, itype: InputType) -> InputType:
        h, w, c = itype.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        if self.padding == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return InputType.convolutional(oh, ow, c)

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pad = self.padding.upper()
        if self.pooling is PoolingType.MAX:
            from deeplearning4j_tpu.runtime.backend import maxpool_fusion_barrier

            y = lax.reduce_window(
                maxpool_fusion_barrier(x), -jnp.inf, lax.max, dims, strides, pad
            )
        elif self.pooling is PoolingType.SUM:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif self.pooling is PoolingType.AVG:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            if pad == "SAME":
                ones = jnp.ones(x.shape[:1] + x.shape[1:], x.dtype)
                cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
                y = s / cnt
            else:
                y = s / (kh * kw)
        elif self.pooling is PoolingType.PNORM:
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
            y = s ** (1.0 / p)
        else:
            raise ValueError(f"unhandled pooling {self.pooling}")
        return y, state


@serde.register
@dataclasses.dataclass(frozen=True)
class GlobalPooling(LayerConfig):
    """GlobalPoolingLayer role: collapse spatial (CNN) or time (RNN) dims."""

    pooling: PoolingType = PoolingType.AVG
    HAS_PARAMS = False
    REGULARIZED = ()
    ACCEPTS_MASK = True

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind == InputType.KIND_CNN:
            return InputType.feed_forward(itype.channels)
        if itype.kind == InputType.KIND_RNN:
            return InputType.feed_forward(itype.size)
        return itype

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        axes = tuple(range(1, x.ndim - 1))
        m = None
        if mask is not None:
            # (B, T) sequence mask broadcast over features; every pooling
            # type must exclude padded steps (the reference masks all four)
            m = mask.astype(x.dtype)
            while m.ndim < x.ndim:
                m = m[..., None]
        if self.pooling is PoolingType.MAX:
            if m is not None:
                x = jnp.where(m > 0, x, jnp.asarray(-jnp.inf, x.dtype))
            return jnp.max(x, axis=axes), state
        if self.pooling is PoolingType.SUM:
            if m is not None:
                x = x * m
            return jnp.sum(x, axis=axes), state
        if self.pooling is PoolingType.PNORM:
            p = 2.0
            if m is not None:
                x = x * m
            return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1 / p), state
        if m is not None:
            denom = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
            return jnp.sum(x * m, axis=axes) / denom, state
        return jnp.mean(x, axis=axes), state


@serde.register
@dataclasses.dataclass(frozen=True)
class SpaceToDepth(LayerConfig):
    """Space-to-depth (the reference's SpaceToDepthLayer; YOLO2's
    'passthrough' reorg).  (B, H, W, C) -> (B, H/b, W/b, C*b^2)."""

    block: int = 2
    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def output_type(self, itype: InputType) -> InputType:
        h, w, c = itype.shape
        b = self.block
        if h % b or w % b:
            raise ValueError(f"spatial dims ({h},{w}) not divisible by block {b}")
        return InputType.convolutional(h // b, w // b, c * b * b)

    def apply(self, params, state, x, *, training=False, rng=None):
        n, h, w, c = x.shape
        b = self.block
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = jnp.transpose(y, (0, 1, 3, 2, 4, 5)).reshape(n, h // b, w // b, c * b * b)
        return y, state


@serde.register
@dataclasses.dataclass(frozen=True)
class ZeroPadding2D(LayerConfig):
    padding: tuple[int, int, int, int] = (1, 1, 1, 1)   # top, bottom, left, right
    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def output_type(self, itype: InputType) -> InputType:
        h, w, c = itype.shape
        t, b, l, r = self.padding
        return InputType.convolutional(h + t + b, w + l + r, c)

    def apply(self, params, state, x, *, training=False, rng=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Upsampling2D(LayerConfig):
    size: tuple[int, int] = (2, 2)
    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def output_type(self, itype: InputType) -> InputType:
        h, w, c = itype.shape
        return InputType.convolutional(h * self.size[0], w * self.size[1], c)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1), self.size[1], axis=2)
        return y, state


# ---------------------------------------------------------------------------
# Normalization layers
# ---------------------------------------------------------------------------

@serde.register
@dataclasses.dataclass(frozen=True)
class BatchNorm(LayerConfig):
    """BatchNormalization role.

    Running mean/var live in layer STATE (the functional analog of the
    reference's mutable running stats); training returns updated state from
    inside the compiled step.  Under data-parallel sharding the batch mean
    is a global mean — GSPMD inserts the cross-replica reduction, which is
    exactly synchronized ("sync BN") semantics.
    """

    epsilon: float = 1e-5
    decay: float = 0.9        # running-stat momentum (reference default 0.9)
    lock_gamma_beta: bool = False

    HAS_PARAMS = True
    REGULARIZED = ()

    def init(self, key, itype):
        c = itype.shape[-1]
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.ones((c,), jnp.float32), "beta": jnp.zeros((c,), jnp.float32)}
        state = {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, training=False, rng=None):
        axes = tuple(range(x.ndim - 1))
        if training:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.epsilon)
        scale = params.get("gamma", 1.0) * inv
        shift = params.get("beta", 0.0) - mean * scale
        y = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
        return self._act()(y), new_state


@serde.register
@dataclasses.dataclass(frozen=True)
class LayerNorm(LayerConfig):
    """Layer normalization over the feature (last) dim."""

    epsilon: float = 1e-5
    HAS_PARAMS = True
    REGULARIZED = ()

    def init(self, key, itype):
        c = itype.shape[-1]
        return {"gamma": jnp.ones((c,), jnp.float32), "beta": jnp.zeros((c,), jnp.float32)}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        y = y * params["gamma"] + params["beta"]
        return self._act()(y.astype(x.dtype)), state


@serde.register
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(LayerConfig):
    """LRN role (AlexNet-era)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75
    EXPECTS = "cnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def apply(self, params, state, x, *, training=False, rng=None):
        sq = x.astype(jnp.float32) ** 2
        half = self.n // 2
        # sum over a window along the channel axis
        padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
        windows = [padded[..., i : i + x.shape[-1]] for i in range(self.n)]
        s = sum(windows)
        y = x.astype(jnp.float32) / (self.k + self.alpha * s) ** self.beta
        return y.astype(x.dtype), state


@serde.register
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(LayerConfig):
    """Softmax + center loss output (reference
    org.deeplearning4j.nn.conf.layers.CenterLossOutputLayer [U], the
    FaceNetNN4Small2 training head): pulls each example's embedding
    toward its class center while the cross-entropy separates classes.

    TPU-native design: the class centers are ordinary trainable params
    inside the compiled step — the center term's gradient wrt `centers`
    IS the center update (scaled by `alpha` against the main loss), so
    no out-of-graph bookkeeping exists.  `apply()` emits
    `concat([logits, embedding])`; use `split_output()` to separate
    them (the embedding half is the face-recognition feature vector).
    """

    n_out: int = 0            # number of classes
    alpha: float = 0.1        # center learning-rate multiplier
    lambda_coeff: float = 2e-4  # weight of the center-distance term
    has_bias: bool = True

    EXPECTS = "ff"

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out + itype.size)

    def init(self, key, itype):
        n_in = itype.size
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in,
                               fan_out=self.n_out)
        params = {"W": w, "centers": jnp.zeros((self.n_out, n_in), jnp.float32)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        logits = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            logits = logits + params["b"].astype(x.dtype)
        return jnp.concatenate([logits, x], axis=-1), state

    def split_output(self, out):
        """(logits, embedding) halves of apply()'s concatenated output."""
        return out[..., : self.n_out], out[..., self.n_out :]

    def evaluation_output(self, lp, out):
        """Class probabilities for Evaluation (argmax over the raw concat
        output would land in the embedding half)."""
        logits, _ = self.split_output(out)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    def compute_loss_with_params(self, lp, preds, labels, mask=None):
        logits, emb = self.split_output(preds.astype(jnp.float32))
        labels = labels.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        per = -jnp.sum(labels * logp, axis=-1)
        # class center per example; alpha scales the gradient that flows
        # into the centers (the reference's center update rate)
        centers = lp["centers"]
        centers = (
            centers * self.alpha + jax.lax.stop_gradient(centers) * (1 - self.alpha)
        )
        c = labels @ centers.astype(jnp.float32)
        center_term = 0.5 * jnp.sum((emb - c) ** 2, axis=-1)
        per = per + self.lambda_coeff * center_term
        if mask is not None:
            m = mask.astype(jnp.float32)
            return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(per)


@serde.register
@dataclasses.dataclass(frozen=True)
class ChunkedSoftmaxOutputLayer(LayerConfig):
    """LM output head whose softmax cross-entropy streams the vocab in
    chunks (ops/chunked_xent.py) — the (N, vocab) logits tensor, the
    largest activation in a large-vocab training step, never
    materializes.  No reference counterpart (the reference always
    buffers dense logits through LossMCXENT); this is TPU HBM headroom
    the dense path cannot offer.

    `apply()` passes hidden states through UNPROJECTED; the loss owns
    the (n_in, vocab) projection.  Labels may be int class ids
    ((B,) / (B,T), the memory-sane form) or one-hot (converted via
    argmax).  For inference, `logits(params, h)` materializes the
    projection densely (generation usually wants top-k of one step,
    not a training batch of logits).
    """

    n_out: int = 0          # vocab size
    chunk: int = 8192
    has_bias: bool = True

    EXPECTS = "any"

    def output_type(self, itype: InputType) -> InputType:
        return itype            # hidden states pass through; loss projects

    def init(self, key, itype):
        n_in = itype.size
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in,
                               fan_out=self.n_out)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        return _dropout(x, self.dropout_rate or 0.0, training, rng), state

    def logits(self, params, h):
        """Dense projection for inference/generation."""
        y = quantf.matmul(h, params["W"])
        if self.has_bias:
            y = y + params["b"].astype(h.dtype)
        return y

    def evaluation_output(self, lp, out):
        """Class probabilities for Evaluation: project the hidden states
        densely (evaluate() batches are inference-sized)."""
        return jax.nn.softmax(self.logits(lp, out).astype(jnp.float32), axis=-1)

    def compute_loss_with_params(self, lp, preds, labels, mask=None):
        from deeplearning4j_tpu.ops.chunked_xent import chunked_softmax_xent

        d = preds.shape[-1]
        h = preds.reshape(-1, d)
        labels = jnp.asarray(labels)
        # disambiguate by ELEMENT COUNT, not trailing-dim match: when the
        # sequence length equals the vocab size, (B, T) int ids would
        # otherwise be misread as (B, V) one-hot
        if labels.size == h.shape[0] * self.n_out:
            labels = jnp.argmax(
                labels.reshape(h.shape[0], self.n_out), axis=-1
            )                                            # one-hot fallback
        elif labels.size != h.shape[0]:
            raise ValueError(
                f"labels with {labels.size} elements fit neither int ids "
                f"({h.shape[0]}) nor one-hot ({h.shape[0]}x{self.n_out})"
            )
        ids = labels.reshape(-1).astype(jnp.int32)
        if mask is not None:
            w = jnp.asarray(mask).reshape(-1).astype(jnp.float32)
        else:
            w = jnp.ones((h.shape[0],), jnp.float32)
        b = lp.get("b", jnp.zeros((self.n_out,), jnp.float32))
        return chunked_softmax_xent(h, lp["W"], b, ids, w, self.chunk)
