"""Attention layers for the config DSL — the reference's attention surface
(`org.deeplearning4j.nn.conf.layers.SelfAttentionLayer`,
`LearnedSelfAttentionLayer`, and the `multi_head_dot_product_attention`
custom op underneath, SURVEY.md §5.7) made first-class and long-context
capable.

The reference runs attention single-device with O(T^2) memory.  Here every
attention layer carries a `seq_parallel` knob ({"none", "ring", "ulysses"},
the SURVEY §5.7 config-knob requirement): when the model was distribute()'d
onto a mesh with a "seq" axis, the attention core lowers to
`ops/attention.py`'s ring (ppermute KV rotation with online softmax) or
Ulysses (all_to_all head scatter) kernel inside a partial-manual shard_map
(manual over "seq", auto over everything else — GSPMD still handles
data/tensor parallelism around it).  On a single chip or a mesh without a
"seq" axis the same layer lowers to dense fused attention; the config is
scale-portable.

Also here: TransformerEncoderBlock, a pre-LN encoder block (MHA + FFN with
residuals) so a DSL-built transformer is a first-class citizen of the zoo.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    LayerConfig,
    LayerNorm,
    _coerce_enum,
    _dropout,
)
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.quant import functional as quantf
from deeplearning4j_tpu.ops.attention import mha, ring_attention, ulysses_attention
from deeplearning4j_tpu.runtime.mesh import SEQ_AXIS, active_mesh, shard_map
from deeplearning4j_tpu.utils import serde

_SEQ_MODES = ("none", "ring", "ulysses")


def _seq_axis_active(mesh) -> bool:
    return (
        mesh is not None
        and SEQ_AXIS in mesh.axis_names
        and mesh.shape[SEQ_AXIS] > 1
    )


def _attend(q, k, v, *, causal: bool, mask, seq_parallel: str):
    """Dispatch the attention core: dense on one shard, ring/ulysses under a
    partial-manual shard_map when a "seq" mesh axis is active.

    q,k,v: (B, T, H, Dh).  mask: (B, T) keep-mask over keys or None.
    """
    if seq_parallel not in _SEQ_MODES:
        raise ValueError(
            f"seq_parallel={seq_parallel!r}; options: {_SEQ_MODES}"
        )
    mesh = active_mesh()
    if seq_parallel == "none" or not _seq_axis_active(mesh):
        return mha(q, k, v, causal=causal, mask=mask)

    n = mesh.shape[SEQ_AXIS]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by seq axis size {n}"
        )
    if seq_parallel == "ulysses" and q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by seq axis ({n})"
        )
    core = ring_attention if seq_parallel == "ring" else ulysses_attention
    spec = P(None, SEQ_AXIS)
    if mask is not None:
        fn = lambda q, k, v, m: core(q, k, v, axis=SEQ_AXIS, causal=causal, mask=m)
        return shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=spec,
            axis_names={SEQ_AXIS},
            check_vma=False,
        )(q, k, v, mask)
    fn = lambda q, k, v: core(q, k, v, axis=SEQ_AXIS, causal=causal)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={SEQ_AXIS},
        check_vma=False,
    )(q, k, v)


def resolve_head_size(n_out: int, n_heads: int, head_size) -> int:
    """Explicit head_size wins; otherwise n_out must split evenly over
    heads.  Shared by SelfAttentionLayer / LearnedSelfAttentionLayer /
    AttentionVertex so head-size semantics can't drift between them."""
    if head_size is not None:
        return head_size
    if n_out % n_heads:
        raise ValueError(f"n_out {n_out} not divisible by n_heads {n_heads}")
    return n_out // n_heads


def init_qkv_params(key, wi: WeightInit, n_in_q: int, n_in_k: int, n_in_v: int,
                    hd: int, n_out: int) -> dict:
    """Wq/Wk/Wv projections into n_heads*head_size (=hd) + Wo back out —
    shared by SelfAttentionLayer and AttentionVertex."""
    kq, kk, kv, ko = jax.random.split(key, 4)
    return {
        "Wq": wi.init(kq, (n_in_q, hd), fan_in=n_in_q, fan_out=hd),
        "Wk": wi.init(kk, (n_in_k, hd), fan_in=n_in_k, fan_out=hd),
        "Wv": wi.init(kv, (n_in_v, hd), fan_in=n_in_v, fan_out=hd),
        "Wo": wi.init(ko, (hd, n_out), fan_in=hd, fan_out=n_out),
    }


def apply_qkv_attention(params, xq, xk, xv, *, n_heads: int, head_size: int,
                        project_input: bool, causal: bool, mask,
                        seq_parallel: str):
    """Project (when project_input), attend, merge heads, project out.
    xq/xk/xv: (B, T*, F) — identical arrays for self-attention."""
    b, tq = xq.shape[0], xq.shape[1]
    h, dh = n_heads, head_size
    dt = xq.dtype
    if project_input:
        q = quantf.matmul(xq, params["Wq"]).reshape(b, tq, h, dh)
        k = quantf.matmul(xk, params["Wk"]).reshape(b, xk.shape[1], h, dh)
        v = quantf.matmul(xv, params["Wv"]).reshape(b, xv.shape[1], h, dh)
    else:
        q = xq.reshape(b, tq, h, dh)
        k = xk.reshape(b, xk.shape[1], h, dh)
        v = xv.reshape(b, xv.shape[1], h, dh)
    out = _attend(q, k, v, causal=causal, mask=mask, seq_parallel=seq_parallel)
    out = out.reshape(b, tq, h * dh)
    if project_input:
        out = quantf.matmul(out, params["Wo"])
    return out


@serde.register
@dataclasses.dataclass(frozen=True)
class SelfAttentionLayer(LayerConfig):
    """Multi-head self-attention over a sequence (SelfAttentionLayer role).

    project_input=True (the useful case): learned Wq/Wk/Wv projections into
    n_heads*head_size, attention, then Wo back out to n_out.
    project_input=False mirrors the reference's constraint: the input is
    used directly as q=k=v, requiring n_in == n_heads*head_size == n_out.
    """

    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None       # default: n_out // n_heads
    project_input: bool = True
    causal: bool = False
    seq_parallel: str = "none"            # none | ring | ulysses

    EXPECTS = "rnn"
    ACCEPTS_MASK = True
    REGULARIZED = ("Wq", "Wk", "Wv", "Wo")

    def _head_size(self) -> int:
        return resolve_head_size(self.n_out, self.n_heads, self.head_size)

    def output_type(self, itype: InputType) -> InputType:
        if not self.project_input and itype.size != self.n_out:
            raise ValueError(
                "project_input=False requires n_in == n_out "
                f"(got {itype.size} vs {self.n_out})"
            )
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init(self, key, itype):
        if not self.project_input:
            if itype.size != self.n_heads * self._head_size():
                raise ValueError(
                    "project_input=False requires n_in == n_heads*head_size "
                    f"(got {itype.size} vs {self.n_heads}*{self._head_size()})"
                )
            return {}, {}
        n_in, hd = itype.size, self.n_heads * self._head_size()
        wi = self._winit(WeightInit.XAVIER)
        return init_qkv_params(key, wi, n_in, n_in, n_in, hd, self.n_out), {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        out = apply_qkv_attention(
            params, x, x, x,
            n_heads=self.n_heads,
            head_size=self._head_size(),
            project_input=self.project_input,
            causal=self.causal,
            mask=mask,
            seq_parallel=self.seq_parallel,
        )
        return self._act()(out), state


@serde.register
@dataclasses.dataclass(frozen=True)
class LearnedSelfAttentionLayer(LayerConfig):
    """Attention with n_queries LEARNED query vectors
    (LearnedSelfAttentionLayer role): output is (B, n_queries, n_out),
    independent of input length — a trainable sequence-pooling layer.

    Sequence parallelism does not apply (queries are a small learned set,
    not a sharded sequence); keys/values are consumed dense.
    """

    n_out: int = 0
    n_heads: int = 1
    n_queries: int = 1
    head_size: Optional[int] = None

    EXPECTS = "rnn"
    ACCEPTS_MASK = True
    REGULARIZED = ("Wk", "Wv", "Wo", "Q")

    def _head_size(self) -> int:
        return resolve_head_size(self.n_out, self.n_heads, self.head_size)

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, self.n_queries)

    def init(self, key, itype):
        n_in, hd = itype.size, self.n_heads * self._head_size()
        kq, kk, kv, ko = jax.random.split(key, 4)
        wi = self._winit(WeightInit.XAVIER)
        return {
            "Q": wi.init(kq, (self.n_queries, hd), fan_in=hd, fan_out=hd),
            "Wk": wi.init(kk, (n_in, hd), fan_in=n_in, fan_out=hd),
            "Wv": wi.init(kv, (n_in, hd), fan_in=n_in, fan_out=hd),
            "Wo": wi.init(ko, (hd, self.n_out), fan_in=hd, fan_out=self.n_out),
        }, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        b, t = x.shape[0], x.shape[1]
        h, dh = self.n_heads, self._head_size()
        q = jnp.broadcast_to(
            params["Q"].astype(x.dtype).reshape(1, self.n_queries, h, dh),
            (b, self.n_queries, h, dh),
        )
        k = (x @ params["Wk"].astype(x.dtype)).reshape(b, t, h, dh)
        v = (x @ params["Wv"].astype(x.dtype)).reshape(b, t, h, dh)
        out = mha(q, k, v, mask=mask)
        out = out.reshape(b, self.n_queries, h * dh) @ params["Wo"].astype(x.dtype)
        return self._act()(out), state


@serde.register
@dataclasses.dataclass(frozen=True)
class PositionalEncoding(LayerConfig):
    """Additive position information for attention stacks: sinusoidal
    (parameterless, any length) or learned (max_length x d table)."""

    learned: bool = False
    max_length: int = 0                 # required when learned=True

    EXPECTS = "rnn"
    REGULARIZED = ()

    @property
    def HAS_PARAMS(self):  # type: ignore[override]
        return self.learned

    def init(self, key, itype):
        if not self.learned:
            return {}, {}
        if self.max_length <= 0:
            raise ValueError("learned PositionalEncoding requires max_length")
        d = itype.size
        wi = self._winit(WeightInit.NORMAL)
        return {"P": wi.init(key, (self.max_length, d), fan_in=d, fan_out=d)}, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        t, d = x.shape[1], x.shape[2]
        if self.learned:
            if t > self.max_length:
                raise ValueError(
                    f"sequence length {t} exceeds max_length {self.max_length}"
                )
            return x + params["P"][:t].astype(x.dtype), state
        pos = jnp.arange(t, dtype=jnp.float32)[:, None]
        div = jnp.exp(
            jnp.arange(0, d, 2, dtype=jnp.float32) * (-jnp.log(10000.0) / d)
        )
        pe = jnp.zeros((t, d), jnp.float32)
        pe = pe.at[:, 0::2].set(jnp.sin(pos * div))
        pe = pe.at[:, 1::2].set(jnp.cos(pos * div[: d // 2]))
        return x + pe.astype(x.dtype), state


@serde.register
@dataclasses.dataclass(frozen=True)
class TransformerEncoderBlock(LayerConfig):
    """Pre-LN transformer encoder block:
    x + MHA(LN(x)), then x + FFN(LN(x)) — the standard composition the
    reference could only express op-by-op in SameDiff.  One DSL layer here
    so zoo transformers stack cleanly; inherits the seq_parallel knob.
    """

    d_model: int = 0
    n_heads: int = 1
    d_ff: int = 0                        # default 4*d_model
    causal: bool = False
    seq_parallel: str = "none"
    ffn_activation: Activation = Activation.GELU

    EXPECTS = "rnn"
    ACCEPTS_MASK = True
    REGULARIZED = ()

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(
            self, "ffn_activation", _coerce_enum(self.ffn_activation, Activation)
        )

    def _attn(self) -> SelfAttentionLayer:
        return SelfAttentionLayer(
            n_out=self.d_model,
            n_heads=self.n_heads,
            causal=self.causal,
            seq_parallel=self.seq_parallel,
            weight_init=self.weight_init,
        )

    def _dff(self) -> int:
        return self.d_ff if self.d_ff > 0 else 4 * self.d_model

    def output_type(self, itype: InputType) -> InputType:
        if itype.size != self.d_model:
            raise ValueError(
                f"TransformerEncoderBlock d_model={self.d_model} but input "
                f"feature size is {itype.size}"
            )
        return InputType.recurrent(self.d_model, itype.shape[0])

    def init(self, key, itype):
        k_attn, k1, k2 = jax.random.split(key, 3)
        ln = LayerNorm()
        attn_p, _ = self._attn().init(k_attn, itype)
        ln1_p, _ = ln.init(None, itype)
        ln2_p, _ = ln.init(None, itype)
        d, dff = self.d_model, self._dff()
        wi = self._winit(WeightInit.XAVIER)
        return {
            "attn": attn_p,
            "ln1": ln1_p,
            "ln2": ln2_p,
            "W1": wi.init(k1, (d, dff), fan_in=d, fan_out=dff),
            "b1": jnp.zeros((dff,), jnp.float32),
            "W2": wi.init(k2, (dff, d), fan_in=dff, fan_out=d),
            "b2": jnp.zeros((d,), jnp.float32),
        }, {}

    def regularizable_params(self, lp):
        out = [lp[p] for p in ("W1", "W2") if p in lp]
        attn = lp.get("attn", {})
        out.extend(attn[p] for p in ("Wq", "Wk", "Wv", "Wo") if p in attn)
        return out

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        ln = LayerNorm()
        attn = self._attn()
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        h, _ = ln.apply(params["ln1"], {}, x)
        h, _ = attn.apply(params["attn"], {}, h, training=training, rng=r1, mask=mask)
        x = x + h
        h, _ = ln.apply(params["ln2"], {}, x)
        h = _dropout(h, self.dropout_rate or 0.0, training, r2)
        h = self.ffn_activation(
            quantf.matmul(h, params["W1"]) + params["b1"].astype(x.dtype)
        )
        h = quantf.matmul(h, params["W2"]) + params["b2"].astype(x.dtype)
        return x + h, state
