"""Mixture-of-Experts layer for the config DSL.

The reference has no MoE at all (SURVEY.md §2.3 "Expert parallel: NO");
this makes the TPU build's expert parallelism reachable from the model
DSL: `MoELayer` is a drop-in FFN-shaped layer for sequence models whose
experts shard over the "expert" mesh axis under
`distribute(model, ParallelConfig(expert=k))` — GSPMD lowers the dispatch
einsums of `parallel/expert.py` to all_to_all over ICI.

The Switch-style load-balancing auxiliary loss rides the aux-loss channel:
apply() emits it under models._common.AUX_LOSS_KEY in the layer state and
the compiled training step adds it to the objective (inference never pays
for it).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import AUX_LOSS_KEY, LayerConfig
from deeplearning4j_tpu.parallel.expert import MoEConfig, init_moe, moe_apply
from deeplearning4j_tpu.utils import serde


@serde.register
@dataclasses.dataclass(frozen=True)
class MoELayer(LayerConfig):
    """Capacity-bounded top-k MoE FFN over a sequence: (B,T,D) -> (B,T,D).

    n_out: d_model (input feature size must match — the layer is a
    residual-position FFN replacement, not a projection).
    """

    n_out: int = 0
    n_experts: int = 8
    d_hidden: int = 0                    # default 4*n_out
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    residual: bool = True                # x + MoE(x), the transformer shape

    EXPECTS = "rnn"
    REGULARIZED = ()                     # expert weights self-regularize via
                                         # the aux loss; l2 on (E,D,H) tensors
                                         # is opt-in through explicit l1/l2
                                         # fields if ever needed

    def _cfg(self) -> MoEConfig:
        return MoEConfig(
            n_experts=self.n_experts,
            d_model=self.n_out,
            d_hidden=self.d_hidden if self.d_hidden > 0 else 4 * self.n_out,
            top_k=self.top_k,
            capacity_factor=self.capacity_factor,
        )

    def output_type(self, itype: InputType) -> InputType:
        if itype.kind != InputType.KIND_RNN:
            raise ValueError(f"MoELayer expects sequence input, got {itype}")
        if itype.size != self.n_out:
            raise ValueError(
                f"MoELayer n_out={self.n_out} must equal the input feature "
                f"size {itype.size} (FFN-shaped layer)"
            )
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init(self, key, itype):
        return init_moe(key, self._cfg()), {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y, aux = moe_apply(params, x, self._cfg())
        if self.residual:
            y = x + y
        ns = {}
        if training and self.aux_loss_weight:
            ns[AUX_LOSS_KEY] = (self.aux_loss_weight * aux).astype(jnp.float32)
        return y, ns
