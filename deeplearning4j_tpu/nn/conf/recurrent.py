"""Recurrent layers — the `org.deeplearning4j.nn.layers.recurrent` role.

The reference's GravesLSTM/LSTM run a per-timestep Java loop issuing cell
ops through JNI (LSTMHelpers.activateHelper — SURVEY.md §5.7); here the
time loop is a `lax.scan` INSIDE the compiled step, with the input
projection x@Wx for ALL timesteps hoisted out of the scan as one large
(B*T, F)x(F, 4H) matmul that rides the MXU; only the small recurrent
h@Wh matmul remains sequential.

Masking (variable-length batches): masked steps pass the carry through
unchanged and output zeros — matching the reference's mask propagation.

Layout: (B, T, F) batch-major in the API; scan runs time-major internally.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig, _dropout
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.utils import serde


class RecurrentLayerConfig(LayerConfig):
    """Base for layers with a time-carry.  Subclasses implement
    init_carry(batch, dtype) and apply_with_carry(...); plain apply()
    starts from a zero carry and discards the final one."""

    EXPECTS = "rnn"
    REGULARIZED = ("Wx", "Wh")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init_carry(self, batch: int, dtype):
        raise NotImplementedError

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        raise NotImplementedError

    ACCEPTS_MASK = True

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        carry = self.init_carry(x.shape[0], x.dtype)
        y, _ = self.apply_with_carry(
            params, x, carry, mask=mask, training=training, rng=rng
        )
        return y, state


def _scan_time_major(cell, carry, x, mask):
    """x: (B,T,...) -> scan over T. Returns (ys (B,T,H), final_carry)."""
    xt = jnp.swapaxes(x, 0, 1)  # (T, B, ...)
    if mask is not None:
        mt = jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None]  # (T, B, 1)
    else:
        mt = jnp.ones((xt.shape[0], xt.shape[1], 1), x.dtype)
    carry, ys = lax.scan(cell, carry, (xt, mt))
    return jnp.swapaxes(ys, 0, 1), carry


@serde.register
@dataclasses.dataclass(frozen=True)
class LSTM(RecurrentLayerConfig):
    """Standard LSTM (the reference's `LSTM` layer).

    Gate order in the fused weight matrices: [i, f, g, o].
    forget_gate_bias=1.0 follows the reference default.
    """

    n_out: int = 0
    forget_gate_bias: float = 1.0
    gate_activation: Activation = Activation.SIGMOID

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        params = {
            "Wx": wi.init(k1, (n_in, 4 * n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, 4 * n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((4 * n_out,), jnp.float32)
            .at[n_out : 2 * n_out]
            .set(self.forget_gate_bias),
        }
        return params, {}

    def init_carry(self, batch, dtype):
        return (
            jnp.zeros((batch, self.n_out), dtype),
            jnp.zeros((batch, self.n_out), dtype),
        )

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        n_out = self.n_out
        act = self._act(Activation.TANH)
        gate_act = self.gate_activation
        wx = params["Wx"].astype(x.dtype)
        wh = params["Wh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        # hoist the input projection out of the scan: one big MXU matmul
        xproj = x @ wx + b  # (B, T, 4H)

        def cell(c, inp):
            (h, cstate) = c
            xt, mt = inp
            z = xt + h @ wh
            i = gate_act(z[..., :n_out])
            f = gate_act(z[..., n_out : 2 * n_out])
            g = act(z[..., 2 * n_out : 3 * n_out])
            o = gate_act(z[..., 3 * n_out :])
            c_new = f * cstate + i * g
            h_new = o * act(c_new)
            c_new = mt * c_new + (1 - mt) * cstate
            h_new = mt * h_new + (1 - mt) * h
            return (h_new, c_new), h_new * mt

        return _scan_time_major(cell, carry, xproj, mask)


@serde.register
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013) — the reference's
    GravesLSTM (BASELINE config 3).  Diagonal peephole weights: c_{t-1}
    feeds i and f gates; c_t feeds the o gate."""

    def init(self, key, itype):
        params, state = super().init(key, itype)
        params["pI"] = jnp.zeros((self.n_out,), jnp.float32)
        params["pF"] = jnp.zeros((self.n_out,), jnp.float32)
        params["pO"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, state

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        n_out = self.n_out
        act = self._act(Activation.TANH)
        gate_act = self.gate_activation
        wx = params["Wx"].astype(x.dtype)
        wh = params["Wh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        pI = params["pI"].astype(x.dtype)
        pF = params["pF"].astype(x.dtype)
        pO = params["pO"].astype(x.dtype)
        xproj = x @ wx + b

        def cell(c, inp):
            (h, cstate) = c
            xt, mt = inp
            z = xt + h @ wh
            i = gate_act(z[..., :n_out] + pI * cstate)
            f = gate_act(z[..., n_out : 2 * n_out] + pF * cstate)
            g = act(z[..., 2 * n_out : 3 * n_out])
            c_new = f * cstate + i * g
            o = gate_act(z[..., 3 * n_out :] + pO * c_new)
            h_new = o * act(c_new)
            c_new = mt * c_new + (1 - mt) * cstate
            h_new = mt * h_new + (1 - mt) * h
            return (h_new, c_new), h_new * mt

        return _scan_time_major(cell, carry, xproj, mask)


@serde.register
@dataclasses.dataclass(frozen=True)
class GRU(RecurrentLayerConfig):
    """GRU cell. Gate order: [r, z, n]."""

    n_out: int = 0

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        return {
            "Wx": wi.init(k1, (n_in, 3 * n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, 3 * n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((3 * n_out,), jnp.float32),
        }, {}

    def init_carry(self, batch, dtype):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        n_out = self.n_out
        act = self._act(Activation.TANH)
        wx = params["Wx"].astype(x.dtype)
        wh = params["Wh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        xproj = x @ wx + b

        def cell(c, inp):
            (h,) = c
            xt, mt = inp
            hz = h @ wh
            r = jax.nn.sigmoid(xt[..., :n_out] + hz[..., :n_out])
            z = jax.nn.sigmoid(xt[..., n_out : 2 * n_out] + hz[..., n_out : 2 * n_out])
            n = act(xt[..., 2 * n_out :] + r * hz[..., 2 * n_out :])
            h_new = (1 - z) * n + z * h
            h_new = mt * h_new + (1 - mt) * h
            return (h_new,), h_new * mt

        return _scan_time_major(cell, carry, xproj, mask)


@serde.register
@dataclasses.dataclass(frozen=True)
class SimpleRnn(RecurrentLayerConfig):
    """Elman RNN (the reference's SimpleRnn)."""

    n_out: int = 0

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        return {
            "Wx": wi.init(k1, (n_in, n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((n_out,), jnp.float32),
        }, {}

    def init_carry(self, batch, dtype):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        act = self._act(Activation.TANH)
        wx = params["Wx"].astype(x.dtype)
        wh = params["Wh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        xproj = x @ wx + b

        def cell(c, inp):
            (h,) = c
            xt, mt = inp
            h_new = act(xt + h @ wh)
            h_new = mt * h_new + (1 - mt) * h
            return (h_new,), h_new * mt

        return _scan_time_major(cell, carry, xproj, mask)


@serde.register
@dataclasses.dataclass(frozen=True)
class Bidirectional(LayerConfig):
    """Bidirectional wrapper (the reference's Bidirectional): runs the
    wrapped RNN forward and time-reversed, combining outputs."""

    layer: Optional[RecurrentLayerConfig] = None
    mode: str = "concat"  # concat | add | mul | ave

    EXPECTS = "rnn"
    ACCEPTS_MASK = True

    def output_type(self, itype: InputType) -> InputType:
        inner = self.layer.output_type(itype)
        size = inner.size * 2 if self.mode == "concat" else inner.size
        return InputType.recurrent(size, itype.shape[0])

    def init(self, key, itype):
        k1, k2 = jax.random.split(key)
        fwd, _ = self.layer.init(k1, itype)
        bwd, _ = self.layer.init(k2, itype)
        return {"fwd": fwd, "bwd": bwd}, {}

    REGULARIZED = ()

    def regularizable_params(self, lp):
        out = []
        for half in ("fwd", "bwd"):
            if half in lp:
                out.extend(self.layer.regularizable_params(lp[half]))
        return out

    def regularization_terms(self, lp):
        # outer coefficients win when set (builder defaults land on the
        # wrapper); otherwise the inner layer's own l1/l2 apply
        l1 = self.l1 if self.l1 is not None else (self.layer.l1 or 0.0)
        l2 = self.l2 if self.l2 is not None else (self.layer.l2 or 0.0)
        if not l1 and not l2:
            return []
        return [(l1, l2, w) for w in self.regularizable_params(lp)]

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        carry = self.layer.init_carry(x.shape[0], x.dtype)
        yf, _ = self.layer.apply_with_carry(
            params["fwd"], x, carry, mask=mask, training=training, rng=rng
        )
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.layer.apply_with_carry(
            params["bwd"], xr, carry, mask=mr, training=training, rng=rng
        )
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.mode == "add":
            return yf + yb, state
        if self.mode == "mul":
            return yf * yb, state
        if self.mode == "ave":
            return (yf + yb) / 2, state
        raise ValueError(f"unknown Bidirectional mode {self.mode}")


@serde.register
@dataclasses.dataclass(frozen=True)
class LastTimeStep(LayerConfig):
    """Collapse (B,T,H) -> (B,H) at the last UNMASKED step per example
    (the reference's LastTimeStep wrapper)."""

    EXPECTS = "rnn"
    HAS_PARAMS = False
    REGULARIZED = ()
    ACCEPTS_MASK = True

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(itype.size)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        if mask is None:
            return x[:, -1, :], state
        # index of the LAST nonzero mask entry per example (sum-1 would be
        # wrong for non-contiguous masks)
        T = x.shape[1]
        idx = T - 1 - jnp.argmax(jnp.flip(mask, axis=1), axis=1)
        idx = jnp.clip(idx.astype(jnp.int32), 0, T - 1)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :], state


@serde.register
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(LayerConfig):
    """Per-timestep dense + loss (the reference's RnnOutputLayer):
    (B,T,H) -> (B,T,n_out) logits; the loss masks padded steps via
    labels_mask."""

    n_out: int = 0
    loss: Loss = Loss.MCXENT
    has_bias: bool = True

    EXPECTS = "rnn"

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init(self, key, itype):
        n_in = itype.size
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        y = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state  # logits; loss/activation handled by the model
