"""Recurrent layers — the `org.deeplearning4j.nn.layers.recurrent` role.

The reference's GravesLSTM/LSTM run a per-timestep Java loop issuing cell
ops through JNI (LSTMHelpers.activateHelper — SURVEY.md §5.7); here the
time loop is a `lax.scan` INSIDE the compiled step, with the input
projection x@Wx for ALL timesteps hoisted out of the scan as one large
(B*T, F)x(F, 4H) matmul that rides the MXU; only the small recurrent
h@Wh matmul remains sequential.

Masking (variable-length batches): masked steps pass the carry through
unchanged and output zeros — matching the reference's mask propagation.

Layout: (B, T, F) batch-major in the API; scan runs time-major internally.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig, _dropout
from deeplearning4j_tpu.nn.losses import Loss
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.quant import functional as quantf
from deeplearning4j_tpu.utils import serde


class RecurrentLayerConfig(LayerConfig):
    """Base for layers with a time-carry.  Subclasses implement
    init_carry(batch, dtype), input_projection / project_step (the hoisted
    input matmul) and cell_step (one recurrence step); apply_with_carry
    scans cell_step over time, and plain apply() starts from a zero carry
    and discards the final one.

    The cell/projection split exists so STACKS of recurrent layers can run
    in ONE lax.scan (`fused_rnn_scan`): the sequential chain is the TPU
    bottleneck (each scan step is latency-, not FLOP-bound), so halving
    the number of scanned steps by interleaving layer cells beats running
    one scan per layer."""

    EXPECTS = "rnn"
    REGULARIZED = ("Wx", "Wh")

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init_carry(self, batch: int, dtype):
        raise NotImplementedError

    def _cast(self, params, dtype):
        return {k: v.astype(dtype) for k, v in params.items()}

    def input_projection(self, cp, x):
        """Hoisted input matmul for the whole sequence: (B,T,F)->(B,T,G)."""
        return x @ cp["Wx"] + cp["b"]

    def project_step(self, cp, h):
        """Per-step input matmul (for fused stacks): (B,F)->(B,G)."""
        return h @ cp["Wx"] + cp["b"]

    def cell_step(self, cp, carry, zin, mt):
        """One recurrence step. zin: projected input (B,G); mt: (B,1) mask.
        Returns (new_carry, output (B,H))."""
        raise NotImplementedError

    def fused_cell_step(self, cp, carry, h_below, mt):
        """One step fed by the RAW lower-layer output (fused stacks).
        Default: project then step (2 matmuls).  Cells whose input and
        recurrent projections are structurally additive (LSTM, SimpleRnn)
        override this with ONE [x;h] @ [Wx;Wh] matmul — the scan chain's
        wall time tracks the number of sequential matmuls, so halving it
        matters more than the matmul's size."""
        return self.cell_step(cp, carry, self.project_step(cp, h_below), mt)

    def apply_with_carry(self, params, x, carry, *, mask=None, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        cp = self._cast(params, x.dtype)
        xproj = self.input_projection(cp, x)

        def cell(c, inp):
            xt, mt = inp
            return self.cell_step(cp, c, xt, mt)

        return _scan_time_major(cell, carry, xproj, mask)

    ACCEPTS_MASK = True

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        carry = self.init_carry(x.shape[0], x.dtype)
        y, _ = self.apply_with_carry(
            params, x, carry, mask=mask, training=training, rng=rng
        )
        return y, state


def fused_rnn_scan(layers, params_list, x, carries, mask, *, training=False,
                   rng=None):
    """Run a STACK of recurrent layers in ONE lax.scan over time.

    Layer k>0's input projection cannot be hoisted (its input is layer
    k-1's output at the same step), so it runs per step — the same matmul
    size as the recurrent term.  What the fusion buys is the sequential
    chain: one scanned step per timestep instead of one per (timestep x
    layer), and on TPU the scan chain is latency-bound, not FLOP-bound.

    Dropout: only the FIRST layer's dropout is applied (to the full
    sequence, pre-hoist); callers must not fuse across a layer with
    dropout.  Returns (ys from the last layer, [final_carry per layer])."""
    x = _dropout(x, layers[0].dropout_rate or 0.0, training, rng)
    cps = [l._cast(p, x.dtype) for l, p in zip(layers, params_list)]
    xproj = layers[0].input_projection(cps[0], x)
    # non-first layers with additive projections get a combined [Wx;Wh]
    # so their per-step input+recurrent matmuls collapse into one
    for cp in cps[1:]:
        if "Wx" in cp and "Wh" in cp:
            cp["WxWh"] = jnp.concatenate([cp["Wx"], cp["Wh"]], axis=0)

    def cell(cs, inp):
        xt, mt = inp
        new_cs = []
        h = None
        for k, (layer, cp) in enumerate(zip(layers, cps)):
            if k == 0:
                ck, h = layer.cell_step(cp, cs[k], xt, mt)
            else:
                ck, h = layer.fused_cell_step(cp, cs[k], h, mt)
            new_cs.append(ck)
        return tuple(new_cs), h

    ys, finals = _scan_time_major(cell, tuple(carries), xproj, mask)
    return ys, list(finals)


def _scan_time_major(cell, carry, x, mask):
    """x: (B,T,...) -> scan over T. Returns (ys (B,T,H), final_carry).

    mask=None is passed through as a STATIC None so cells skip the three
    per-step blend ops entirely — on TPU the scan chain is launch-bound
    and unmasked training (the common case) shouldn't pay for masking."""
    xt = jnp.swapaxes(x, 0, 1)  # (T, B, ...)
    if mask is not None:
        mt = jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None]  # (T, B, 1)
        carry, ys = lax.scan(cell, carry, (xt, mt))
    else:
        carry, ys = lax.scan(lambda c, xt_: cell(c, (xt_, None)), carry, xt)
    return jnp.swapaxes(ys, 0, 1), carry


@serde.register
@dataclasses.dataclass(frozen=True)
class LSTM(RecurrentLayerConfig):
    """Standard LSTM (the reference's `LSTM` layer).

    Gate order in the fused weight matrices: [i, f, g, o].
    forget_gate_bias=1.0 follows the reference default.
    """

    n_out: int = 0
    forget_gate_bias: float = 1.0
    gate_activation: Activation = Activation.SIGMOID

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        params = {
            "Wx": wi.init(k1, (n_in, 4 * n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, 4 * n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((4 * n_out,), jnp.float32)
            .at[n_out : 2 * n_out]
            .set(self.forget_gate_bias),
        }
        return params, {}

    def init_carry(self, batch, dtype):
        return (
            jnp.zeros((batch, self.n_out), dtype),
            jnp.zeros((batch, self.n_out), dtype),
        )

    def _gates(self, cp, z, carry, mt):
        (h, cstate) = carry
        n_out = self.n_out
        act = self._act(Activation.TANH)
        gate_act = self.gate_activation
        i = gate_act(z[..., :n_out])
        f = gate_act(z[..., n_out : 2 * n_out])
        g = act(z[..., 2 * n_out : 3 * n_out])
        o = gate_act(z[..., 3 * n_out :])
        c_new = f * cstate + i * g
        h_new = o * act(c_new)
        if mt is None:
            return (h_new, c_new), h_new
        c_new = mt * c_new + (1 - mt) * cstate
        h_new = mt * h_new + (1 - mt) * h
        return (h_new, c_new), h_new * mt

    def cell_step(self, cp, carry, zin, mt):
        z = zin + carry[0] @ cp["Wh"]
        return self._gates(cp, z, carry, mt)

    def fused_cell_step(self, cp, carry, h_below, mt):
        z = jnp.concatenate([h_below, carry[0]], axis=-1) @ cp["WxWh"] + cp["b"]
        return self._gates(cp, z, carry, mt)


@serde.register
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013) — the reference's
    GravesLSTM (BASELINE config 3).  Diagonal peephole weights: c_{t-1}
    feeds i and f gates; c_t feeds the o gate."""

    def init(self, key, itype):
        params, state = super().init(key, itype)
        params["pI"] = jnp.zeros((self.n_out,), jnp.float32)
        params["pF"] = jnp.zeros((self.n_out,), jnp.float32)
        params["pO"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, state

    def _gates(self, cp, z, carry, mt):
        (h, cstate) = carry
        n_out = self.n_out
        act = self._act(Activation.TANH)
        gate_act = self.gate_activation
        i = gate_act(z[..., :n_out] + cp["pI"] * cstate)
        f = gate_act(z[..., n_out : 2 * n_out] + cp["pF"] * cstate)
        g = act(z[..., 2 * n_out : 3 * n_out])
        c_new = f * cstate + i * g
        o = gate_act(z[..., 3 * n_out :] + cp["pO"] * c_new)
        h_new = o * act(c_new)
        if mt is None:
            return (h_new, c_new), h_new
        c_new = mt * c_new + (1 - mt) * cstate
        h_new = mt * h_new + (1 - mt) * h
        return (h_new, c_new), h_new * mt


@serde.register
@dataclasses.dataclass(frozen=True)
class GRU(RecurrentLayerConfig):
    """GRU cell. Gate order: [r, z, n]."""

    n_out: int = 0

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        return {
            "Wx": wi.init(k1, (n_in, 3 * n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, 3 * n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((3 * n_out,), jnp.float32),
        }, {}

    def init_carry(self, batch, dtype):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def cell_step(self, cp, carry, zin, mt):
        (h,) = carry
        n_out = self.n_out
        act = self._act(Activation.TANH)
        hz = h @ cp["Wh"]
        if "bh" in cp:
            # recurrent bias (Keras GRU reset_after=True carries separate
            # input/recurrent biases; the recurrent one applies INSIDE the
            # reset gating of the candidate)
            hz = hz + cp["bh"]
        r = jax.nn.sigmoid(zin[..., :n_out] + hz[..., :n_out])
        z = jax.nn.sigmoid(zin[..., n_out : 2 * n_out] + hz[..., n_out : 2 * n_out])
        n = act(zin[..., 2 * n_out :] + r * hz[..., 2 * n_out :])
        h_new = (1 - z) * n + z * h
        if mt is None:
            return (h_new,), h_new
        h_new = mt * h_new + (1 - mt) * h
        return (h_new,), h_new * mt


@serde.register
@dataclasses.dataclass(frozen=True)
class SimpleRnn(RecurrentLayerConfig):
    """Elman RNN (the reference's SimpleRnn)."""

    n_out: int = 0

    def init(self, key, itype):
        n_in, n_out = itype.size, self.n_out
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        return {
            "Wx": wi.init(k1, (n_in, n_out), fan_in=n_in, fan_out=n_out),
            "Wh": wi.init(k2, (n_out, n_out), fan_in=n_out, fan_out=n_out),
            "b": jnp.zeros((n_out,), jnp.float32),
        }, {}

    def init_carry(self, batch, dtype):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def cell_step(self, cp, carry, zin, mt):
        (h,) = carry
        act = self._act(Activation.TANH)
        h_new = act(zin + h @ cp["Wh"])
        if mt is None:
            return (h_new,), h_new
        h_new = mt * h_new + (1 - mt) * h
        return (h_new,), h_new * mt

    def fused_cell_step(self, cp, carry, h_below, mt):
        (h,) = carry
        act = self._act(Activation.TANH)
        z = jnp.concatenate([h_below, h], axis=-1) @ cp["WxWh"] + cp["b"]
        h_new = act(z)
        if mt is None:
            return (h_new,), h_new
        h_new = mt * h_new + (1 - mt) * h
        return (h_new,), h_new * mt


@serde.register
@dataclasses.dataclass(frozen=True)
class Bidirectional(LayerConfig):
    """Bidirectional wrapper (the reference's Bidirectional): runs the
    wrapped RNN forward and time-reversed, combining outputs."""

    layer: Optional[RecurrentLayerConfig] = None
    mode: str = "concat"  # concat | add | mul | ave
    # False = keras Bidirectional(return_sequences=False): emit
    # combine(fwd final step, bwd final step) as (B, size) — note the
    # backward half's final step corresponds to ORIGINAL index 0, which is
    # why Bidirectional + LastTimeStep is NOT equivalent
    return_sequences: bool = True

    EXPECTS = "rnn"
    ACCEPTS_MASK = True

    def output_type(self, itype: InputType) -> InputType:
        inner = self.layer.output_type(itype)
        size = inner.size * 2 if self.mode == "concat" else inner.size
        if not self.return_sequences:
            return InputType.feed_forward(size)
        return InputType.recurrent(size, itype.shape[0])

    def init(self, key, itype):
        k1, k2 = jax.random.split(key)
        fwd, _ = self.layer.init(k1, itype)
        bwd, _ = self.layer.init(k2, itype)
        return {"fwd": fwd, "bwd": bwd}, {}

    REGULARIZED = ()

    def regularizable_params(self, lp):
        out = []
        for half in ("fwd", "bwd"):
            if half in lp:
                out.extend(self.layer.regularizable_params(lp[half]))
        return out

    def regularization_terms(self, lp):
        # outer coefficients win when set (builder defaults land on the
        # wrapper); otherwise the inner layer's own l1/l2 apply
        l1 = self.l1 if self.l1 is not None else (self.layer.l1 or 0.0)
        l2 = self.l2 if self.l2 is not None else (self.layer.l2 or 0.0)
        if not l1 and not l2:
            return []
        return [(l1, l2, w) for w in self.regularizable_params(lp)]

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        carry = self.layer.init_carry(x.shape[0], x.dtype)
        yf, _ = self.layer.apply_with_carry(
            params["fwd"], x, carry, mask=mask, training=training, rng=rng
        )
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.layer.apply_with_carry(
            params["bwd"], xr, carry, mask=mr, training=training, rng=rng
        )
        yb = jnp.flip(yb, axis=1)
        if not self.return_sequences:
            # fwd final = last unmasked step; bwd final = the backward
            # pass's own last step, i.e. original index 0 after unflip
            if mask is None:
                yf = yf[:, -1, :]
            else:
                T = yf.shape[1]
                idx = T - 1 - jnp.argmax(jnp.flip(mask, axis=1), axis=1)
                idx = jnp.clip(idx.astype(jnp.int32), 0, T - 1)
                yf = jnp.take_along_axis(yf, idx[:, None, None], axis=1)[:, 0, :]
            yb = yb[:, 0, :]
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.mode == "add":
            return yf + yb, state
        if self.mode == "mul":
            return yf * yb, state
        if self.mode == "ave":
            return (yf + yb) / 2, state
        raise ValueError(f"unknown Bidirectional mode {self.mode}")


@serde.register
@dataclasses.dataclass(frozen=True)
class LastTimeStep(LayerConfig):
    """Collapse (B,T,H) -> (B,H) at the last UNMASKED step per example
    (the reference's LastTimeStep wrapper)."""

    EXPECTS = "rnn"
    HAS_PARAMS = False
    REGULARIZED = ()
    ACCEPTS_MASK = True

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(itype.size)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        if mask is None:
            return x[:, -1, :], state
        # index of the LAST nonzero mask entry per example (sum-1 would be
        # wrong for non-contiguous masks)
        T = x.shape[1]
        idx = T - 1 - jnp.argmax(jnp.flip(mask, axis=1), axis=1)
        idx = jnp.clip(idx.astype(jnp.int32), 0, T - 1)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :], state


@serde.register
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(LayerConfig):
    """Per-timestep dense + loss (the reference's RnnOutputLayer):
    (B,T,H) -> (B,T,n_out) logits; the loss masks padded steps via
    labels_mask."""

    n_out: int = 0
    loss: Loss = Loss.MCXENT
    has_bias: bool = True

    EXPECTS = "rnn"

    def output_type(self, itype: InputType) -> InputType:
        return InputType.recurrent(self.n_out, itype.shape[0])

    def init(self, key, itype):
        n_in = itype.size
        w = self._winit().init(key, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        y = quantf.matmul(x, params["W"])
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return y, state  # logits; loss/activation handled by the model


@serde.register
@dataclasses.dataclass(frozen=True)
class TimeDistributed(LayerConfig):
    """Apply a feed-forward layer independently at every timestep of a
    (B, T, F) sequence, preserving the time axis (the reference's
    TimeDistributedLayer wrapper / keras TimeDistributed).  The wrapped
    layer must be a feed-forward kind; parameters are SHARED across
    timesteps (one inner init)."""

    layer: Optional[LayerConfig] = None

    EXPECTS = "rnn"

    def __post_init__(self):
        if self.layer is not None and self.layer.EXPECTS not in ("ff", "any"):
            raise ValueError(
                "TimeDistributed wraps feed-forward layers; got a layer "
                f"expecting {self.layer.EXPECTS!r}"
            )

    def output_type(self, itype: InputType) -> InputType:
        inner = self.layer.output_type(InputType.feed_forward(itype.size))
        return InputType.recurrent(inner.size, itype.shape[0])

    def init(self, key, itype):
        return self.layer.init(key, InputType.feed_forward(itype.size))

    def regularizable_params(self, lp):
        return self.layer.regularizable_params(lp)

    def regularization_terms(self, lp):
        return self.layer.regularization_terms(lp)

    def apply(self, params, state, x, *, training=False, rng=None):
        # ff layers are pointwise over leading axes (x @ W broadcasts), so
        # (B, T, F) passes straight through — no reshape round trip
        return self.layer.apply(params, state, x, training=training, rng=rng)


@serde.register
@dataclasses.dataclass(frozen=True)
class ConvLSTM2D(LayerConfig):
    """Convolutional LSTM over image sequences (keras ConvLSTM2D; the
    reference imports it via KerasConvLstm2D).  Input is the CNN3D kind
    (B, T, H, W, C) with depth read as time; gates are convolutions:
    z = conv(x_t, Wx) + conv(h, Wh), gate order [i, f, g, o].  The input
    conv honors `padding`; the recurrent conv is always SAME (state keeps
    the output's spatial dims), matching keras.  One lax.scan over time —
    XLA unrolls nothing and the MXU sees every conv."""

    n_out: int = 0                      # filters
    kernel: tuple[int, int] = (3, 3)
    stride: tuple[int, int] = (1, 1)
    padding: str = "valid"
    return_sequences: bool = False
    forget_gate_bias: float = 1.0

    EXPECTS = "cnn3d"

    def _out_hw(self, h: int, w: int) -> tuple[int, int]:
        kh, kw = self.kernel
        sh, sw = self.stride
        if self.padding == "same":
            return -(-h // sh), -(-w // sw)
        return (h - kh) // sh + 1, (w - kw) // sw + 1

    def output_type(self, itype: InputType) -> InputType:
        t, h, w, _ = itype.shape
        oh, ow = self._out_hw(h, w)
        if self.return_sequences:
            return InputType.convolutional3d(t, oh, ow, self.n_out)
        return InputType.convolutional(oh, ow, self.n_out)

    def init(self, key, itype):
        c_in = itype.shape[-1]
        kh, kw = self.kernel
        k1, k2 = jax.random.split(key)
        wi = self._winit(WeightInit.XAVIER)
        f = self.n_out
        params = {
            "Wx": wi.init(k1, (kh, kw, c_in, 4 * f),
                          fan_in=kh * kw * c_in, fan_out=kh * kw * f),
            "Wh": wi.init(k2, (kh, kw, f, 4 * f),
                          fan_in=kh * kw * f, fan_out=kh * kw * f),
            "b": jnp.zeros((4 * f,), jnp.float32)
            .at[f: 2 * f]
            .set(self.forget_gate_bias),
        }
        return params, {}

    def _conv(self, x, w, stride, padding):
        return lax.conv_general_dilated(
            x, w, window_strides=stride, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        f = self.n_out
        wx = params["Wx"].astype(x.dtype)
        wh = params["Wh"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        pad = "SAME" if self.padding == "same" else "VALID"
        B, T, H, W, _ = x.shape
        oh, ow = self._out_hw(H, W)
        sigmoid = jax.nn.sigmoid

        def step(carry, xt):
            h, c = carry
            z = (self._conv(xt, wx, self.stride, pad)
                 + self._conv(h, wh, (1, 1), "SAME") + b)
            i = sigmoid(z[..., :f])
            fg = sigmoid(z[..., f:2 * f])
            g = jnp.tanh(z[..., 2 * f:3 * f])
            o = sigmoid(z[..., 3 * f:])
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        h0 = jnp.zeros((B, oh, ow, f), x.dtype)
        carry, ys = lax.scan(step, (h0, h0), jnp.swapaxes(x, 0, 1))
        if self.return_sequences:
            return jnp.swapaxes(ys, 0, 1), state
        return carry[0], state
