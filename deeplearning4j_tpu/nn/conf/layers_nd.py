"""1-D / 3-D conv-family layers, croppings, and PReLU.

Reference parity (SURVEY.md §2.2 "DL4J-NN config DSL"): Convolution1D,
Convolution3D, Subsampling1DLayer, Subsampling3DLayer,
Cropping1D/2D/3D, PReLULayer.  Same pure init/apply contract as
layers.py; sequence (1-D) layers ride the RNN input kind (B, T, C) — the
TPU layout keeps channels last at every rank so every conv contraction
feeds the MXU lanes directly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig, PoolingType
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.quant import functional as quantf
from deeplearning4j_tpu.utils import serde


def _triple(v) -> tuple[int, int, int]:
    if isinstance(v, int):
        return (v, v, v)
    t = tuple(int(x) for x in v)
    if len(t) != 3:
        raise ValueError(f"need an int or 3-tuple, got {v}")
    return t


def _out_len(size: int, k: int, s: int, padding: str, d: int = 1) -> int:
    eff = (k - 1) * d + 1
    if padding == "same":
        return -(-size // s)
    return -(-(size - eff + 1) // s)


@serde.register
@dataclasses.dataclass(frozen=True)
class Conv1D(LayerConfig):
    """Temporal convolution over (B, T, C) — `Convolution1DLayer`."""

    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    padding: str = "same"
    dilation: int = 1
    has_bias: bool = True

    EXPECTS = "rnn"

    def output_type(self, itype: InputType) -> InputType:
        t = itype.shape[0]
        t_out = (
            -1 if t < 0
            else _out_len(t, self.kernel, self.stride, self.padding, self.dilation)
        )
        return InputType.recurrent(self.n_out, t_out)

    def init(self, key, itype):
        c_in = itype.size
        fan_in = self.kernel * c_in
        w = self._winit(WeightInit.RELU).init(
            key, (self.kernel, c_in, self.n_out),
            fan_in=fan_in, fan_out=self.kernel * self.n_out,
        )
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, quantf.conv_weight(params["W"], x.dtype),
            window_strides=(self.stride,),
            padding=self.padding.upper(),
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act()(y), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Conv3D(LayerConfig):
    """Volumetric convolution over (B, D, H, W, C) — `Convolution3D`."""

    n_out: int = 0
    kernel: tuple[int, int, int] = (3, 3, 3)
    stride: tuple[int, int, int] = (1, 1, 1)
    padding: str = "same"
    has_bias: bool = True

    EXPECTS = "cnn3d"

    def output_type(self, itype: InputType) -> InputType:
        d, h, w, _ = itype.shape
        kd, kh, kw = _triple(self.kernel)
        sd, sh, sw = _triple(self.stride)
        return InputType.convolutional3d(
            _out_len(d, kd, sd, self.padding),
            _out_len(h, kh, sh, self.padding),
            _out_len(w, kw, sw, self.padding),
            self.n_out,
        )

    def init(self, key, itype):
        c_in = itype.channels
        kd, kh, kw = _triple(self.kernel)
        fan_in = kd * kh * kw * c_in
        w = self._winit(WeightInit.RELU).init(
            key, (kd, kh, kw, c_in, self.n_out),
            fan_in=fan_in, fan_out=kd * kh * kw * self.n_out,
        )
        params = {"W": w}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), jnp.float32)
        return params, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, quantf.conv_weight(params["W"], x.dtype),
            window_strides=_triple(self.stride),
            padding=self.padding.upper(),
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        )
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return self._act()(y), state


def _pool_nd(x, kind: PoolingType, window, strides, padding: str,
             pnorm: float = 2.0):
    """All four reference pooling kinds (mirrors the 2D Subsampling)."""
    dims = (1, *window, 1)
    strd = (1, *strides, 1)
    pad = padding.upper()
    if kind == PoolingType.MAX:
        from deeplearning4j_tpu.runtime.backend import maxpool_fusion_barrier

        return lax.reduce_window(
            maxpool_fusion_barrier(x), -jnp.inf, lax.max, dims, strd, pad
        )
    if kind == PoolingType.SUM:
        return lax.reduce_window(x, 0.0, lax.add, dims, strd, pad)
    if kind == PoolingType.AVG:
        s = lax.reduce_window(x, 0.0, lax.add, dims, strd, pad)
        if pad == "SAME":
            cnt = lax.reduce_window(
                jnp.ones_like(x), 0.0, lax.add, dims, strd, pad
            )
            return s / cnt
        denom = 1
        for w in window:
            denom *= w
        return s / denom
    if kind == PoolingType.PNORM:
        p = float(pnorm)
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strd, pad)
        return s ** (1.0 / p)
    raise ValueError(f"unhandled pooling {kind}")


@serde.register
@dataclasses.dataclass(frozen=True)
class Subsampling1D(LayerConfig):
    """Temporal pooling over (B, T, C) — `Subsampling1DLayer`."""

    kernel: int = 2
    stride: int = 2
    padding: str = "valid"
    pooling: PoolingType = PoolingType.MAX
    pnorm: float = 2.0

    EXPECTS = "rnn"
    HAS_PARAMS = False

    def output_type(self, itype: InputType) -> InputType:
        t = itype.shape[0]
        t_out = -1 if t < 0 else _out_len(t, self.kernel, self.stride, self.padding)
        return InputType.recurrent(itype.size, t_out)

    def apply(self, params, state, x, *, training=False, rng=None):
        return _pool_nd(x, self.pooling, (self.kernel,), (self.stride,),
                        self.padding, self.pnorm), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Subsampling3D(LayerConfig):
    """Volumetric pooling over (B, D, H, W, C) — `Subsampling3DLayer`."""

    kernel: tuple[int, int, int] = (2, 2, 2)
    stride: tuple[int, int, int] = (2, 2, 2)
    padding: str = "valid"
    pooling: PoolingType = PoolingType.MAX
    pnorm: float = 2.0

    EXPECTS = "cnn3d"
    HAS_PARAMS = False

    def output_type(self, itype: InputType) -> InputType:
        d, h, w, c = itype.shape
        kd, kh, kw = _triple(self.kernel)
        sd, sh, sw = _triple(self.stride)
        return InputType.convolutional3d(
            _out_len(d, kd, sd, self.padding),
            _out_len(h, kh, sh, self.padding),
            _out_len(w, kw, sw, self.padding),
            c,
        )

    def apply(self, params, state, x, *, training=False, rng=None):
        return _pool_nd(x, self.pooling, _triple(self.kernel),
                        _triple(self.stride), self.padding, self.pnorm), state


def _crop2(v) -> tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    t = tuple(int(x) for x in v)
    return (t[0], t[1]) if len(t) == 2 else (t[0], t[0])


@serde.register
@dataclasses.dataclass(frozen=True)
class Cropping1D(LayerConfig):
    """Trim (begin, end) timesteps — `Cropping1D`."""

    cropping: tuple[int, int] = (0, 0)

    EXPECTS = "rnn"
    HAS_PARAMS = False

    def output_type(self, itype: InputType) -> InputType:
        t = itype.shape[0]
        a, b = _crop2(self.cropping)
        return InputType.recurrent(itype.size, t if t < 0 else t - a - b)

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = _crop2(self.cropping)
        return x[:, a : x.shape[1] - b, :], state


@serde.register
@dataclasses.dataclass(frozen=True)
class Cropping2D(LayerConfig):
    """Trim ((top, bottom), (left, right)) pixels — `Cropping2D`."""

    cropping: tuple = ((0, 0), (0, 0))

    EXPECTS = "cnn"
    HAS_PARAMS = False

    def _hw(self):
        c = self.cropping
        if isinstance(c, int):
            return (c, c), (c, c)
        c = tuple(c)
        if isinstance(c[0], int):
            return (c[0], c[0]), (c[1], c[1])
        return _crop2(c[0]), _crop2(c[1])

    def output_type(self, itype: InputType) -> InputType:
        h, w, ch = itype.shape
        (t, b), (l, r) = self._hw()
        return InputType.convolutional(h - t - b, w - l - r, ch)

    def apply(self, params, state, x, *, training=False, rng=None):
        (t, b), (l, r) = self._hw()
        return x[:, t : x.shape[1] - b, l : x.shape[2] - r, :], state


@serde.register
@dataclasses.dataclass(frozen=True)
class Cropping3D(LayerConfig):
    """Trim ((d0,d1),(h0,h1),(w0,w1)) voxels — `Cropping3D`."""

    cropping: tuple = ((0, 0), (0, 0), (0, 0))

    EXPECTS = "cnn3d"
    HAS_PARAMS = False

    def _dhw(self):
        c = self.cropping
        if isinstance(c, int):
            return ((c, c),) * 3
        c = tuple(c)
        if isinstance(c[0], int):
            return tuple((v, v) for v in _triple(c))
        return tuple(_crop2(v) for v in c)

    def output_type(self, itype: InputType) -> InputType:
        d, h, w, ch = itype.shape
        (d0, d1), (h0, h1), (w0, w1) = self._dhw()
        return InputType.convolutional3d(d - d0 - d1, h - h0 - h1, w - w0 - w1, ch)

    def apply(self, params, state, x, *, training=False, rng=None):
        (d0, d1), (h0, h1), (w0, w1) = self._dhw()
        return (
            x[:, d0 : x.shape[1] - d1, h0 : x.shape[2] - h1,
              w0 : x.shape[3] - w1, :],
            state,
        )


@serde.register
@dataclasses.dataclass(frozen=True)
class PReLU(LayerConfig):
    """Parametric ReLU with a learnable per-channel slope — `PReLULayer`."""

    alpha_init: float = 0.25

    EXPECTS = "any"
    REGULARIZED = ()            # slopes are not weight-decayed (reference
                                # behavior: decay pulls them to dead ReLU)

    def _n_channels(self, itype: InputType) -> int:
        if itype.kind in (InputType.KIND_CNN, InputType.KIND_CNN3D):
            return itype.channels
        return itype.size

    def init(self, key, itype):
        return {
            "alpha": jnp.full((self._n_channels(itype),), self.alpha_init,
                              jnp.float32)
        }, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        a = params["alpha"].astype(x.dtype)
        return jnp.where(x >= 0, x, a * x), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Upsampling1D(LayerConfig):
    """Nearest-neighbor upsampling along the time axis (Upsampling1D
    role): (B, T, C) -> (B, T*size, C)."""

    size: int = 2
    EXPECTS = "rnn"
    HAS_PARAMS = False
    REGULARIZED = ()

    def output_type(self, itype: InputType) -> InputType:
        t = itype.shape[0]
        return InputType.recurrent(itype.size, t if t < 0 else t * self.size)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.repeat(x, self.size, axis=1), state


@serde.register
@dataclasses.dataclass(frozen=True)
class Upsampling3D(LayerConfig):
    """Nearest-neighbor volumetric upsampling (Upsampling3D role):
    (B, D, H, W, C) -> each spatial dim repeated by its factor."""

    size: tuple = (2, 2, 2)
    EXPECTS = "cnn3d"
    HAS_PARAMS = False
    REGULARIZED = ()

    def __post_init__(self):
        super().__post_init__()
        s = self.size
        if isinstance(s, int):
            s = (s, s, s)
        object.__setattr__(self, "size", tuple(int(v) for v in s))

    def output_type(self, itype: InputType) -> InputType:
        d, h, w, c = itype.shape
        sd, sh, sw = self.size
        return InputType.convolutional3d(d * sd, h * sh, w * sw, c)

    def apply(self, params, state, x, *, training=False, rng=None):
        sd, sh, sw = self.size
        y = jnp.repeat(x, sd, axis=1)
        y = jnp.repeat(y, sh, axis=2)
        return jnp.repeat(y, sw, axis=3), state


@serde.register
@dataclasses.dataclass(frozen=True)
class MaskZeroLayer(LayerConfig):
    """Zero out padded timesteps (MaskZeroLayer role): activations at
    mask==0 positions become `mask_value` so downstream layers never see
    padding garbage.  The reference wraps an inner layer; here masking is
    its own stack element (the wrapped layer simply precedes it)."""

    mask_value: float = 0.0
    EXPECTS = "rnn"
    HAS_PARAMS = False
    ACCEPTS_MASK = True
    REGULARIZED = ()

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        if mask is None:
            return x, state
        keep = mask.astype(x.dtype)[:, :, None]
        return x * keep + (1.0 - keep) * self.mask_value, state
