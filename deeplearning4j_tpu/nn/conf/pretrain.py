"""Unsupervised-pretraining layers: AutoEncoder + VariationalAutoencoder.

Reference roles (SURVEY.md §2.2 "Early stopping / transfer learning /
pretraining" — "VAE & pretrain layer support"):
  - org.deeplearning4j.nn.conf.layers.AutoEncoder [U] — denoising
    autoencoder with tied decoder weights (BasePretrainNetwork family).
  - org.deeplearning4j.nn.conf.layers.variational.VariationalAutoencoder
    [U] — multi-layer encoder/decoder VAE with a pluggable reconstruction
    distribution, pretrained on the ELBO.

TPU-native design: the reference gives each pretrain layer its own
backprop implementation driven by MultiLayerNetwork.pretrainLayer()'s
op-at-a-time loop.  Here a pretrainable layer declares ONE extra pure
function, `pretrain_loss(params, x, rng) -> scalar`, and the model
compiles (prefix-forward -> pretrain_loss -> grad -> updater) into a
single donated-buffer XLA step per layer (models/sequential.py
pretrain_layer()).  The supervised `apply()` path is the encoder only,
so a pretrained stack drops straight into fine-tuning.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import Activation
from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig, _dropout
from deeplearning4j_tpu.nn.losses import Loss, compute as compute_loss
from deeplearning4j_tpu.utils import serde


@serde.register
@dataclasses.dataclass(frozen=True)
class AutoEncoder(LayerConfig):
    """Denoising autoencoder with tied decoder weights.

    Supervised forward = encoder only: act(x @ W + b).  `pretrain_loss`
    corrupts the input (masking noise with probability
    `corruption_level`), encodes, decodes through the TIED transpose
    weight plus a visible bias, and scores reconstruction with `loss`
    (reference default: reconstruction cross-entropy for unit-interval
    data; MSE otherwise).  An optional KL sparsity penalty pulls mean
    hidden activation toward `sparsity` (reference's sparsity field).
    """

    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    sparsity_beta: float = 0.0
    loss: Loss = Loss.MSE

    EXPECTS = "ff"
    PRETRAINABLE = True

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        n_in = itype.size
        kw, = jax.random.split(key, 1)
        w = self._winit().init(kw, (n_in, self.n_out), fan_in=n_in, fan_out=self.n_out)
        return {
            "W": w,
            "b": jnp.zeros((self.n_out,), jnp.float32),
            "vb": jnp.zeros((n_in,), jnp.float32),
        }, {}

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        y = x @ params["W"].astype(x.dtype) + params["b"].astype(x.dtype)
        return self._act(Activation.SIGMOID)(y), state

    def _decode(self, params, h):
        """Tied-weight decoder: h @ W^T + vb."""
        return h @ params["W"].astype(h.dtype).T + params["vb"].astype(h.dtype)

    def pretrain_loss(self, params, x, rng) -> jax.Array:
        x = x.astype(jnp.float32)
        if self.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            x_in = jnp.where(keep, x, 0.0)
        else:
            x_in = x
        h = self._act(Activation.SIGMOID)(
            x_in @ params["W"] + params["b"]
        )
        recon = self._decode(params, h)
        if self.loss in (Loss.XENT, Loss.RECONSTRUCTION_CROSSENTROPY):
            loss = compute_loss(Loss.XENT, recon, x, None, from_logits=True)
        else:
            loss = compute_loss(self.loss, recon, x, None, from_logits=False)
        if self.sparsity_beta > 0.0:
            rho, rho_hat = self.sparsity, jnp.clip(jnp.mean(h, axis=0), 1e-6, 1 - 1e-6)
            kl = rho * jnp.log(rho / rho_hat) + (1 - rho) * jnp.log(
                (1 - rho) / (1 - rho_hat)
            )
            loss = loss + self.sparsity_beta * jnp.sum(kl)
        return loss

    def reconstruction_error(self, params, x) -> jax.Array:
        """Per-example reconstruction error (reference
        AutoEncoder score / anomaly-detection usage)."""
        x = x.astype(jnp.float32)
        h = self._act(Activation.SIGMOID)(x @ params["W"] + params["b"])
        recon = self._decode(params, h)
        if self.loss in (Loss.XENT, Loss.RECONSTRUCTION_CROSSENTROPY):
            p = jax.nn.sigmoid(recon)
            return -jnp.sum(
                x * jnp.log(jnp.clip(p, 1e-7, 1.0))
                + (1 - x) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0)),
                axis=-1,
            )
        return jnp.sum((recon - x) ** 2, axis=-1)


def _mlp_init(key, sizes, winit):
    params = {}
    keys = jax.random.split(key, max(len(sizes) - 1, 1))
    for i in range(len(sizes) - 1):
        n_in, n_out = sizes[i], sizes[i + 1]
        params[f"W{i}"] = winit.init(keys[i], (n_in, n_out), fan_in=n_in, fan_out=n_out)
        params[f"b{i}"] = jnp.zeros((n_out,), jnp.float32)
    return params


def _mlp_apply(params, x, act, n_layers):
    for i in range(n_layers):
        x = act(x @ params[f"W{i}"].astype(x.dtype) + params[f"b{i}"].astype(x.dtype))
    return x


@serde.register
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(LayerConfig):
    """Variational autoencoder pretrained on the ELBO.

    `n_out` is the latent size; `encoder_layer_sizes` /
    `decoder_layer_sizes` are the hidden MLP stacks (reference's
    encoderLayerSizes/decoderLayerSizes).  `reconstruction_distribution`
    is "gaussian" (learned diagonal variance) or "bernoulli" (sigmoid
    logits), the reference's pluggable ReconstructionDistribution.
    `num_samples` Monte-Carlo samples estimate the reconstruction term.

    Supervised forward = mean of q(z|x) with `pzx_activation` applied
    (the reference feeds the posterior mean into downstream layers).
    """

    n_out: int = 0
    encoder_layer_sizes: tuple = (100,)
    decoder_layer_sizes: tuple = (100,)
    reconstruction_distribution: str = "gaussian"
    num_samples: int = 1
    pzx_activation: Optional[Activation] = None

    EXPECTS = "ff"
    PRETRAINABLE = True
    REGULARIZED = ()

    def __post_init__(self):
        super().__post_init__()
        object.__setattr__(self, "encoder_layer_sizes",
                           tuple(int(s) for s in self.encoder_layer_sizes))
        object.__setattr__(self, "decoder_layer_sizes",
                           tuple(int(s) for s in self.decoder_layer_sizes))
        if self.pzx_activation is not None:
            from deeplearning4j_tpu.nn.conf.layers import _coerce_enum

            object.__setattr__(
                self, "pzx_activation", _coerce_enum(self.pzx_activation, Activation)
            )
        if self.reconstruction_distribution not in ("gaussian", "bernoulli"):
            raise ValueError(
                "reconstruction_distribution must be 'gaussian' or 'bernoulli', "
                f"got {self.reconstruction_distribution!r}"
            )

    def output_type(self, itype: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, itype):
        n_in = itype.size
        winit = self._winit()
        k_enc, k_mu, k_lv, k_dec, k_out, k_out_lv = jax.random.split(key, 6)
        enc_sizes = (n_in,) + self.encoder_layer_sizes
        dec_sizes = (self.n_out,) + self.decoder_layer_sizes
        e_last, d_last = enc_sizes[-1], dec_sizes[-1]
        params = {
            "enc": _mlp_init(k_enc, enc_sizes, winit),
            "W_mu": winit.init(k_mu, (e_last, self.n_out)),
            "b_mu": jnp.zeros((self.n_out,), jnp.float32),
            "W_lv": winit.init(k_lv, (e_last, self.n_out)),
            "b_lv": jnp.zeros((self.n_out,), jnp.float32),
            "dec": _mlp_init(k_dec, dec_sizes, winit),
            "W_out": winit.init(k_out, (d_last, n_in)),
            "b_out": jnp.zeros((n_in,), jnp.float32),
        }
        if self.reconstruction_distribution == "gaussian":
            params["W_out_lv"] = winit.init(k_out_lv, (d_last, n_in))
            params["b_out_lv"] = jnp.zeros((n_in,), jnp.float32)
        return params, {}

    # -- pieces ------------------------------------------------------------
    def _posterior(self, params, x):
        h = _mlp_apply(params["enc"], x, self._act(Activation.RELU),
                       len(self.encoder_layer_sizes))
        mu = h @ params["W_mu"].astype(h.dtype) + params["b_mu"].astype(h.dtype)
        logvar = h @ params["W_lv"].astype(h.dtype) + params["b_lv"].astype(h.dtype)
        return mu, logvar

    def _decode(self, params, z):
        h = _mlp_apply(params["dec"], z, self._act(Activation.RELU),
                       len(self.decoder_layer_sizes))
        out = h @ params["W_out"].astype(h.dtype) + params["b_out"].astype(h.dtype)
        if self.reconstruction_distribution == "gaussian":
            out_lv = (
                h @ params["W_out_lv"].astype(h.dtype)
                + params["b_out_lv"].astype(h.dtype)
            )
            return out, out_lv
        return out, None

    def apply(self, params, state, x, *, training=False, rng=None):
        x = _dropout(x, self.dropout_rate or 0.0, training, rng)
        mu, _ = self._posterior(params, x)
        act = self.pzx_activation if self.pzx_activation is not None else Activation.IDENTITY
        return act(mu), state

    def _recon_log_prob(self, params, z, x):
        """log p(x|z), summed over features — per example."""
        mean, logvar = self._decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            logp = x * jax.nn.log_sigmoid(mean) + (1 - x) * jax.nn.log_sigmoid(-mean)
            return jnp.sum(logp, axis=-1)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        return -0.5 * jnp.sum(
            logvar + jnp.log(2 * jnp.pi) + (x - mean) ** 2 / jnp.exp(logvar),
            axis=-1,
        )

    def pretrain_loss(self, params, x, rng) -> jax.Array:
        """Negative ELBO, averaged over the batch."""
        x = x.astype(jnp.float32)
        mu, logvar = self._posterior(params, x)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        kl = -0.5 * jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
        recon = 0.0
        for s in range(max(self.num_samples, 1)):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            recon = recon + self._recon_log_prob(params, z, x)
        recon = recon / max(self.num_samples, 1)
        return jnp.mean(kl - recon)

    def reconstruction_log_probability(self, params, x, rng, num_samples=None):
        """Importance-sampled estimate of log p(x) per example (reference
        VariationalAutoencoder.reconstructionLogProbability)."""
        x = jnp.asarray(x, jnp.float32)
        n = int(num_samples or self.num_samples or 1)
        mu, logvar = self._posterior(params, x)
        logvar = jnp.clip(logvar, -10.0, 10.0)
        ws = []
        for s in range(n):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            log_pxz = self._recon_log_prob(params, z, x)
            log_pz = -0.5 * jnp.sum(z**2 + jnp.log(2 * jnp.pi), axis=-1)
            log_qzx = -0.5 * jnp.sum(
                logvar + jnp.log(2 * jnp.pi) + eps**2, axis=-1
            )
            ws.append(log_pxz + log_pz - log_qzx)
        return jax.nn.logsumexp(jnp.stack(ws), axis=0) - jnp.log(float(n))

    def generate(self, params, z):
        """Decode latents to the data space (reference
        generateAtMeanGivenZ)."""
        mean, _ = self._decode(params, jnp.asarray(z, jnp.float32))
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(mean)
        return mean
