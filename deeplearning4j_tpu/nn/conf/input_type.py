"""InputType shape-inference system — the `org.deeplearning4j.nn.conf.inputs.InputType` role.

Layers declare output_type(input_type); the model walks the chain once at
build time so users never specify nIn by hand (`setInputType` semantics).
Convolutional types are NHWC — the TPU-native layout (XLA tiles the last
(lane) dimension onto the MXU; channels-last keeps the contraction dim
contiguous).  The reference is NCHW; layout is an implementation choice,
not a capability, so we pick the TPU-fast one.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.utils import serde


@dataclasses.dataclass(frozen=True)
class InputType:
    KIND_FF = "ff"
    KIND_CNN = "cnn"
    KIND_RNN = "rnn"
    KIND_CNN3D = "cnn3d"

    kind: str = KIND_FF
    # FF: (size,) ; RNN: (timesteps, size) with timesteps -1 = variable ;
    # CNN: (height, width, channels) ; CNN3D: (d, h, w, channels)
    shape: tuple[int, ...] = (0,)

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(InputType.KIND_FF, (int(size),))

    @staticmethod
    def recurrent(size: int, timesteps: int = -1) -> "InputType":
        return InputType(InputType.KIND_RNN, (int(timesteps), int(size)))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(InputType.KIND_CNN, (int(height), int(width), int(channels)))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(InputType.KIND_CNN3D, (int(depth), int(height), int(width), int(channels)))

    # -- accessors ---------------------------------------------------------
    @property
    def size(self) -> int:
        """Feature size of FF/RNN types."""
        if self.kind == self.KIND_FF:
            return self.shape[0]
        if self.kind == self.KIND_RNN:
            return self.shape[1]
        raise ValueError(f"size undefined for {self}")

    @property
    def channels(self) -> int:
        if self.kind in (self.KIND_CNN, self.KIND_CNN3D):
            return self.shape[-1]
        raise ValueError(f"channels undefined for {self}")

    @property
    def flat_size(self) -> int:
        n = 1
        for s in self.shape:
            if s < 0:
                raise ValueError(f"cannot flatten variable dimension in {self}")
            n *= s
        return n

    def batch_shape(self, batch: int) -> tuple[int, ...]:
        return (batch, *self.shape)

    def __repr__(self) -> str:
        return f"InputType({self.kind}, {self.shape})"


serde.register(InputType)
