"""Graph (DAG) configuration — the `ComputationGraphConfiguration` role.

The reference builds DAGs of GraphVertex (LayerVertex wrapping a Layer;
MergeVertex concat; ElementWiseVertex add/... — ResNet skip connections are
ElementWiseVertex(Op.Add); SURVEY.md §3.2) with a GraphBuilder DSL.  Same
capability here: named vertices, multi-input/multi-output, topological-order
walk computed once at build, JSON round-trip.  At runtime the whole DAG is
traced into one XLA computation — topology costs nothing per step.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.input_type import InputType
from deeplearning4j_tpu.nn.conf.layers import LayerConfig
from deeplearning4j_tpu.nn.updaters import Sgd, Updater
from deeplearning4j_tpu.utils import serde


class ElementWiseOp(str, enum.Enum):
    ADD = "add"
    SUBTRACT = "subtract"
    PRODUCT = "product"
    AVERAGE = "average"
    MAX = "max"


@dataclasses.dataclass(frozen=True)
class VertexConfig:
    """Base graph vertex: pure function of its input tensors.

    Vertices with HAS_PARAMS=True additionally implement
    init(key, itypes) -> params and receive `params=` in apply()
    (the reference's parameterized GraphVertex pattern, e.g.
    AttentionVertex).
    """

    HAS_PARAMS = False
    REGULARIZED = ()      # class attr, not a field (stays out of serde)

    def output_type(self, itypes: list[InputType]) -> InputType:
        raise NotImplementedError

    def init(self, key, itypes: list[InputType]) -> dict:
        return {}

    def apply(self, xs: list, **kwargs):
        raise NotImplementedError

    def regularization_terms(self, lp: dict) -> list:
        """(l1, l2, array) triples — parameterized vertices participate in
        the net's l1/l2 penalty exactly like layers do."""
        l1 = getattr(self, "l1", None) or 0.0
        l2 = getattr(self, "l2", None) or 0.0
        if not l1 and not l2:
            return []
        return [(l1, l2, lp[p]) for p in self.REGULARIZED if p in lp]


@serde.register
@dataclasses.dataclass(frozen=True)
class MergeVertex(VertexConfig):
    """Concatenate along the feature (last) axis.

    axis=-1 is the only concat this vertex performs; a non-negative
    `declared_axis` (e.g. carried over from an imported config that spelled
    the trailing axis positionally) is VALIDATED against the input rank at
    type-inference time and rejected if it isn't the trailing axis.
    """

    declared_axis: int = -1

    _RANK = {
        InputType.KIND_FF: 2,
        InputType.KIND_RNN: 3,
        InputType.KIND_CNN: 4,
        InputType.KIND_CNN3D: 5,
    }

    def output_type(self, itypes):
        first = itypes[0]
        if self.declared_axis != -1:
            rank = self._RANK.get(first.kind, 2)
            norm = (
                self.declared_axis
                if self.declared_axis >= 0
                else rank + self.declared_axis
            )
            if norm != rank - 1:
                raise ValueError(
                    f"MergeVertex concatenates the trailing axis only; "
                    f"declared axis {self.declared_axis} on rank-{rank} "
                    "input is not the trailing axis"
                )
        if first.kind == InputType.KIND_FF:
            return InputType.feed_forward(sum(t.size for t in itypes))
        if first.kind == InputType.KIND_CNN:
            h, w, _ = first.shape
            for t in itypes[1:]:
                if t.shape[:2] != (h, w):
                    raise ValueError(f"MergeVertex spatial mismatch: {itypes}")
            return InputType.convolutional(h, w, sum(t.channels for t in itypes))
        if first.kind == InputType.KIND_RNN:
            return InputType.recurrent(sum(t.size for t in itypes), first.shape[0])
        raise ValueError(f"MergeVertex: unsupported {first}")

    def apply(self, xs, **kwargs):
        return jnp.concatenate(xs, axis=-1)


@serde.register
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(VertexConfig):
    op: ElementWiseOp = ElementWiseOp.ADD

    def output_type(self, itypes):
        first = itypes[0]
        for t in itypes[1:]:
            if t.shape != first.shape:
                raise ValueError(f"ElementWiseVertex shape mismatch: {itypes}")
        return first

    def apply(self, xs, **kwargs):
        out = xs[0]
        for x in xs[1:]:
            if self.op is ElementWiseOp.ADD:
                out = out + x
            elif self.op is ElementWiseOp.SUBTRACT:
                out = out - x
            elif self.op is ElementWiseOp.PRODUCT:
                out = out * x
            elif self.op is ElementWiseOp.MAX:
                out = jnp.maximum(out, x)
            elif self.op is ElementWiseOp.AVERAGE:
                out = out + x
            else:
                raise ValueError(f"unhandled {self.op}")
        if self.op is ElementWiseOp.AVERAGE:
            out = out / len(xs)
        return out


@serde.register
@dataclasses.dataclass(frozen=True)
class SubsetVertex(VertexConfig):
    """Feature-range slice [frm, to] inclusive (reference SubsetVertex)."""

    frm: int = 0
    to: int = 0

    def output_type(self, itypes):
        t = itypes[0]
        n = self.to - self.frm + 1
        if t.kind == InputType.KIND_FF:
            return InputType.feed_forward(n)
        if t.kind == InputType.KIND_RNN:
            return InputType.recurrent(n, t.shape[0])
        if t.kind == InputType.KIND_CNN:
            return InputType.convolutional(t.shape[0], t.shape[1], n)
        raise ValueError(f"SubsetVertex: unsupported {t}")

    def apply(self, xs, **kwargs):
        return xs[0][..., self.frm : self.to + 1]


@serde.register
@dataclasses.dataclass(frozen=True)
class ScaleVertex(VertexConfig):
    scale: float = 1.0

    def output_type(self, itypes):
        return itypes[0]

    def apply(self, xs, **kwargs):
        return xs[0] * jnp.asarray(self.scale, xs[0].dtype)


@serde.register
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(VertexConfig):
    epsilon: float = 1e-8

    def output_type(self, itypes):
        return itypes[0]

    def apply(self, xs, **kwargs):
        x = xs[0]
        n = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True))
        return (x / jnp.maximum(n, self.epsilon).astype(x.dtype)).astype(x.dtype)


@serde.register
@dataclasses.dataclass(frozen=True)
class StackVertex(VertexConfig):
    """Stack inputs along the batch axis (reference StackVertex) — the
    inverse of UnstackVertex; used for shared-weight multi-branch nets."""

    def output_type(self, itypes):
        first = itypes[0]
        for t in itypes[1:]:
            if t.shape != first.shape:
                raise ValueError(f"StackVertex shape mismatch: {itypes}")
        return first

    def apply(self, xs, **kwargs):
        return jnp.concatenate(xs, axis=0)


@serde.register
@dataclasses.dataclass(frozen=True)
class UnstackVertex(VertexConfig):
    """Slice #from of `stack_size` equal batch chunks (reference
    UnstackVertex)."""

    index: int = 0
    stack_size: int = 1

    def output_type(self, itypes):
        if not (0 <= self.index < self.stack_size):
            raise ValueError(
                f"UnstackVertex index {self.index} out of range for "
                f"stack_size {self.stack_size}"
            )
        return itypes[0]

    def apply(self, xs, **kwargs):
        x = xs[0]
        if x.shape[0] % self.stack_size:
            raise ValueError(
                f"UnstackVertex: batch {x.shape[0]} not divisible by "
                f"stack_size {self.stack_size}"
            )
        n = x.shape[0] // self.stack_size
        return x[self.index * n : (self.index + 1) * n]


@serde.register
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(VertexConfig):
    """Reshape to a fixed per-example shape (reference ReshapeVertex);
    -1 wildcards allowed in the trailing position."""

    shape: tuple[int, ...] = ()

    def output_type(self, itypes):
        t = itypes[0]
        s = list(self.shape)
        if sum(1 for d in s if d == -1) > 1:
            raise ValueError(f"ReshapeVertex: at most one -1 in {self.shape}")
        if -1 in s:
            # resolve the wildcard against the known per-example size
            fixed = 1
            for d in s:
                if d != -1:
                    fixed *= d
            if t.flat_size % fixed:
                raise ValueError(
                    f"ReshapeVertex: cannot reshape {t.flat_size} elements "
                    f"into {self.shape}"
                )
            s[s.index(-1)] = t.flat_size // fixed
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        raise ValueError(f"ReshapeVertex: unsupported target shape {s}")

    def apply(self, xs, **kwargs):
        x = xs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))


@serde.register
@dataclasses.dataclass(frozen=True)
class AttentionVertex(VertexConfig):
    """Multi-head dot-product attention over (queries, keys, values) inputs
    (the reference's AttentionVertex wrapping the
    multi_head_dot_product_attention op).  1 input => self-attention;
    2 inputs => (q, kv); 3 inputs => (q, k, v).  Projections Wq/Wk/Wv/Wo
    when project_input (recommended).  Carries the same seq_parallel knob
    as SelfAttentionLayer."""

    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True
    causal: bool = False
    seq_parallel: str = "none"
    weight_init: Optional[object] = None
    l1: Optional[float] = None
    l2: Optional[float] = None

    HAS_PARAMS = True
    REGULARIZED = ("Wq", "Wk", "Wv", "Wo")

    def _head_size(self) -> int:
        from deeplearning4j_tpu.nn.conf.attention import resolve_head_size

        return resolve_head_size(self.n_out, self.n_heads, self.head_size)

    def output_type(self, itypes):
        tq = itypes[0]
        if tq.kind != InputType.KIND_RNN:
            raise ValueError(f"AttentionVertex expects RNN inputs, got {tq}")
        if not self.project_input and self.n_out != self.n_heads * self._head_size():
            # without Wo the activation width IS n_heads*head_size
            raise ValueError(
                "project_input=False requires n_out == n_heads*head_size "
                f"({self.n_heads}*{self._head_size()}), got {self.n_out}"
            )
        return InputType.recurrent(self.n_out, tq.shape[0])

    def init(self, key, itypes):
        from deeplearning4j_tpu.nn.conf.attention import init_qkv_params
        from deeplearning4j_tpu.nn.weights import WeightInit

        tq = itypes[0]
        tk = itypes[1] if len(itypes) > 1 else tq
        tv = itypes[2] if len(itypes) > 2 else tk
        hd = self.n_heads * self._head_size()
        if not self.project_input:
            for t in (tq, tk, tv):
                if t.size != hd:
                    raise ValueError(
                        "project_input=False requires every input size == "
                        f"n_heads*head_size ({hd}), got {t.size}"
                    )
            return {}
        wi = self.weight_init if self.weight_init is not None else WeightInit.XAVIER
        if not isinstance(wi, WeightInit):
            wi = WeightInit(wi)
        return init_qkv_params(key, wi, tq.size, tk.size, tv.size, hd, self.n_out)

    def apply(self, xs, params=None, **kwargs):
        from deeplearning4j_tpu.nn.conf.attention import apply_qkv_attention

        xq = xs[0]
        xk = xs[1] if len(xs) > 1 else xq
        xv = xs[2] if len(xs) > 2 else xk
        return apply_qkv_attention(
            params or {}, xq, xk, xv,
            n_heads=self.n_heads,
            head_size=self._head_size(),
            project_input=self.project_input,
            causal=self.causal,
            mask=None,
            seq_parallel=self.seq_parallel,
        )


@serde.register
@dataclasses.dataclass(frozen=True)
class GraphNode:
    """A named node: either a layer or a structural vertex, plus its inputs.

    param_key: parameter-sharing handle — nodes with the same param_key
    read (and train) ONE param/state set (the reference's shared-layer
    topology, e.g. a Keras layer called on several inputs).  None = the
    node's own name (no sharing)."""

    name: str = ""
    inputs: tuple[str, ...] = ()
    layer: Optional[LayerConfig] = None
    vertex: Optional[VertexConfig] = None
    param_key: Optional[str] = None

    @property
    def pkey(self) -> str:
        return self.param_key or self.name

    def __post_init__(self):
        if (self.layer is None) == (self.vertex is None):
            raise ValueError(f"node {self.name}: exactly one of layer/vertex required")


@serde.register
@dataclasses.dataclass(frozen=True)
class GraphConfiguration:
    """Resolved DAG config (ComputationGraphConfiguration role)."""

    nodes: tuple[GraphNode, ...] = ()
    network_inputs: tuple[str, ...] = ()
    network_outputs: tuple[str, ...] = ()
    input_types: tuple[InputType, ...] = ()
    updater: Updater = dataclasses.field(default_factory=Sgd)
    seed: int = 0
    gradient_clip_value: Optional[float] = None
    gradient_clip_norm: Optional[float] = None
    bf16_compute: Optional[bool] = None
    steps_per_epoch: int = 1

    def to_json(self) -> str:
        return serde.dumps(self)

    @staticmethod
    def from_json(s: str) -> "GraphConfiguration":
        cfg = serde.loads(s)
        if not isinstance(cfg, GraphConfiguration):
            raise TypeError(f"JSON did not decode to GraphConfiguration: {type(cfg)}")
        return cfg

    # -- topology ----------------------------------------------------------
    def topological_order(self) -> list[GraphNode]:
        by_name = {n.name: n for n in self.nodes}
        for n in self.nodes:
            for i in n.inputs:
                if i not in by_name and i not in self.network_inputs:
                    raise ValueError(f"node {n.name}: unknown input {i!r}")
        order: list[GraphNode] = []
        state: dict[str, int] = {}  # 0=unvisited 1=visiting 2=done
        net_inputs = set(self.network_inputs)

        def visit(root: str):
            # iterative DFS: deep linear chains must not hit the Python
            # recursion limit
            stack: list[tuple[str, bool]] = [(root, False)]
            while stack:
                name, expanded = stack.pop()
                if name in net_inputs or state.get(name) == 2:
                    continue
                if expanded:
                    state[name] = 2
                    order.append(by_name[name])
                    continue
                if state.get(name) == 1:
                    raise ValueError(f"cycle involving {name!r}")
                state[name] = 1
                stack.append((name, True))
                for i in by_name[name].inputs:
                    if state.get(i) == 1 and i not in net_inputs:
                        raise ValueError(f"cycle involving {i!r}")
                    stack.append((i, False))

        for out in self.network_outputs:
            if out not in by_name:
                raise ValueError(f"network output {out!r} is not a node")
            visit(out)
        # include nodes not reachable from outputs (the reference warns;
        # we include them so their params exist — harmless under XLA DCE)
        for n in self.nodes:
            visit(n.name)
        return order

    def infer_types(self) -> tuple[dict[str, InputType], dict[str, bool]]:
        """Type of every node's OUTPUT + whether an implicit CNN->FF flatten
        precedes each layer node (single source of truth, as in the
        sequential walk)."""
        types: dict[str, InputType] = dict(zip(self.network_inputs, self.input_types))
        flatten: dict[str, bool] = {}
        for node in self.topological_order():
            in_types = [types[i] for i in node.inputs]
            if node.layer is not None:
                t = in_types[0]
                flat = node.layer.EXPECTS == "ff" and t.kind in (
                    InputType.KIND_CNN,
                    InputType.KIND_CNN3D,
                )
                flatten[node.name] = flat
                if flat:
                    t = InputType.feed_forward(t.flat_size)
                types[node.name] = node.layer.output_type(t)
            else:
                flatten[node.name] = False
                types[node.name] = node.vertex.output_type(in_types)
        return types, flatten

class GraphBuilder:
    """Fluent DAG builder (ComputationGraphConfiguration.GraphBuilder role).

        conf = (GraphBuilder()
                .add_inputs("in")
                .set_input_types(InputType.convolutional(32, 32, 3))
                .add_layer("c1", Conv2D(n_out=16, kernel=(3,3)), "in")
                .add_layer("c2", Conv2D(n_out=16, kernel=(3,3), padding="same"), "c1")
                .add_vertex("skip", ElementWiseVertex(ElementWiseOp.ADD), "c1", "c2")
                .add_layer("out", OutputLayer(n_out=10), "skip")
                .set_outputs("out")
                .updater(Adam(1e-3))
                .build())
    """

    def __init__(self):
        self._nodes: list[GraphNode] = []
        self._inputs: tuple[str, ...] = ()
        self._outputs: tuple[str, ...] = ()
        self._input_types: tuple[InputType, ...] = ()
        self._updater: Updater = Sgd()
        self._seed = 0
        self._clip_value: Optional[float] = None
        self._clip_norm: Optional[float] = None
        self._bf16: Optional[bool] = None
        self._steps_per_epoch = 1
        # layer-level defaults (same semantics as NeuralNetConfiguration)
        self._activation = None
        self._weight_init = None
        self._l1 = None
        self._l2 = None
        self._dropout = None

    def add_inputs(self, *names: str):
        self._inputs = tuple(names)
        return self

    def set_input_types(self, *types: InputType):
        self._input_types = tuple(types)
        return self

    def add_layer(self, name: str, layer: LayerConfig, *inputs: str,
                  param_key: str | None = None):
        """param_key: share parameters with every other node carrying the
        same key (shared-layer topology); the layer configs must agree."""
        layer = self._fill_defaults(name, layer)
        self._nodes.append(GraphNode(name=name, inputs=tuple(inputs),
                                     layer=layer, param_key=param_key))
        return self

    def add_vertex(self, name: str, vertex: VertexConfig, *inputs: str):
        # global l1/l2 defaults flow into parameterized vertices exactly as
        # into layers (an AttentionVertex must not silently dodge the
        # net-wide penalty)
        if vertex.HAS_PARAMS:
            updates = {}
            fields = {f.name for f in dataclasses.fields(vertex)}
            if "l1" in fields and vertex.l1 is None and self._l1 is not None:
                updates["l1"] = self._l1
            if "l2" in fields and vertex.l2 is None and self._l2 is not None:
                updates["l2"] = self._l2
            if updates:
                vertex = dataclasses.replace(vertex, **updates)
        self._nodes.append(GraphNode(name=name, inputs=tuple(inputs), vertex=vertex))
        return self

    def set_outputs(self, *names: str):
        self._outputs = tuple(names)
        return self

    def replace_layer(self, name: str, layer: LayerConfig):
        """Swap the layer config of an existing node (e.g. promoting a
        Dense tail to an OutputLayer during model import)."""
        if not any(n.name == name for n in self._nodes):
            raise ValueError(f"no node named {name!r}")
        self._nodes = [
            dataclasses.replace(n, layer=layer) if n.name == name else n
            for n in self._nodes
        ]
        return self

    def updater(self, u: Updater):
        self._updater = u
        return self

    def seed(self, s: int):
        self._seed = int(s)
        return self

    def activation(self, a):
        self._activation = a
        return self

    def weight_init(self, w):
        self._weight_init = w
        return self

    def l1(self, v: float):
        self._l1 = v
        return self

    def l2(self, v: float):
        self._l2 = v
        return self

    def dropout(self, rate: float):
        self._dropout = rate
        return self

    def gradient_clip(self, value: float | None = None, norm: float | None = None):
        self._clip_value, self._clip_norm = value, norm
        return self

    def bf16_compute(self, on: bool):
        self._bf16 = on
        return self

    def steps_per_epoch(self, n: int):
        self._steps_per_epoch = max(1, int(n))
        return self

    def _fill_defaults(self, name: str, layer: LayerConfig) -> LayerConfig:
        updates = {}
        is_output = hasattr(layer, "loss")
        if layer.activation is None and self._activation is not None and not is_output:
            updates["activation"] = self._activation
        if layer.weight_init is None and self._weight_init is not None:
            updates["weight_init"] = self._weight_init
        if layer.l1 is None and self._l1 is not None:
            updates["l1"] = self._l1
        if layer.l2 is None and self._l2 is not None:
            updates["l2"] = self._l2
        if layer.dropout_rate is None and self._dropout is not None:
            updates["dropout_rate"] = self._dropout
        updates["name"] = name
        return dataclasses.replace(layer, **updates)

    def build(self) -> GraphConfiguration:
        if not self._nodes:
            raise ValueError("no nodes configured")
        if not self._inputs:
            raise ValueError("no network inputs declared (add_inputs)")
        if not self._outputs:
            raise ValueError("no network outputs declared (set_outputs)")
        if len(self._input_types) != len(self._inputs):
            raise ValueError(
                f"{len(self._inputs)} inputs but {len(self._input_types)} input types"
            )
        names = [n.name for n in self._nodes] + list(self._inputs)
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate node names: {sorted(dupes)}")
        conf = GraphConfiguration(
            nodes=tuple(self._nodes),
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            updater=self._updater,
            seed=self._seed,
            gradient_clip_value=self._clip_value,
            gradient_clip_norm=self._clip_norm,
            bf16_compute=self._bf16,
            steps_per_epoch=self._steps_per_epoch,
        )
        conf.topological_order()  # validates acyclicity + input references
        conf.infer_types()        # validates shapes compose
        return conf
