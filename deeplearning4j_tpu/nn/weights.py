"""Weight initialization schemes — the `org.deeplearning4j.nn.weights.WeightInit` role.

Fan-in/fan-out are derived from the shape the same way the reference's
`WeightInitUtil` does; every scheme is a pure function of a PRNG key.
"""

from __future__ import annotations

import enum
import math

import jax
import jax.numpy as jnp
import numpy as np


class WeightInit(str, enum.Enum):
    XAVIER = "xavier"              # glorot normal
    XAVIER_UNIFORM = "xavier_uniform"
    RELU = "relu"                  # he normal
    RELU_UNIFORM = "relu_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMAL = "normal"              # N(0, 1/sqrt(fan_in))
    UNIFORM = "uniform"            # U(-a, a), a = 1/sqrt(fan_in)
    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    IDENTITY = "identity"
    ORTHOGONAL = "orthogonal"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"

    def init(
        self,
        key: jax.Array,
        shape: tuple[int, ...],
        fan_in: int | None = None,
        fan_out: int | None = None,
        dtype=jnp.float32,
        constant: float = 0.0,
    ) -> jax.Array:
        if fan_in is None or fan_out is None:
            fi, fo = _fans(shape)
            fan_in = fan_in if fan_in is not None else fi
            fan_out = fan_out if fan_out is not None else fo
        w = self
        if w is WeightInit.ZERO:
            return jnp.zeros(shape, dtype)
        if w is WeightInit.ONES:
            return jnp.ones(shape, dtype)
        if w is WeightInit.CONSTANT:
            return jnp.full(shape, constant, dtype)
        if w is WeightInit.IDENTITY:
            if len(shape) != 2 or shape[0] != shape[1]:
                raise ValueError(f"IDENTITY init needs a square 2D shape, got {shape}")
            return jnp.eye(shape[0], dtype=dtype)
        if w is WeightInit.ORTHOGONAL:
            return jax.nn.initializers.orthogonal()(key, shape, dtype)
        if w is WeightInit.XAVIER:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            return std * jax.random.normal(key, shape, dtype)
        if w is WeightInit.XAVIER_UNIFORM:
            a = math.sqrt(6.0 / (fan_in + fan_out))
            return jax.random.uniform(key, shape, dtype, -a, a)
        if w is WeightInit.RELU:
            std = math.sqrt(2.0 / fan_in)
            return std * jax.random.normal(key, shape, dtype)
        if w is WeightInit.RELU_UNIFORM:
            a = math.sqrt(6.0 / fan_in)
            return jax.random.uniform(key, shape, dtype, -a, a)
        if w is WeightInit.LECUN_NORMAL:
            std = math.sqrt(1.0 / fan_in)
            return std * jax.random.normal(key, shape, dtype)
        if w is WeightInit.LECUN_UNIFORM:
            a = math.sqrt(3.0 / fan_in)
            return jax.random.uniform(key, shape, dtype, -a, a)
        if w is WeightInit.NORMAL:
            return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
        if w is WeightInit.UNIFORM:
            a = 1.0 / math.sqrt(fan_in)
            return jax.random.uniform(key, shape, dtype, -a, a)
        if w is WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            return std * jax.random.normal(key, shape, dtype)
        raise ValueError(f"unhandled WeightInit {w}")


def _fans(shape: tuple[int, ...]) -> tuple[int, int]:
    """(fan_in, fan_out) for dense [in,out] and conv [kh,kw,in,out] shapes."""
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive
