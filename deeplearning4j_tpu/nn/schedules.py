"""Learning-rate schedules — the `org.nd4j.linalg.schedule.ISchedule` role.

Each schedule is a JSON-serializable dataclass that lowers to an optax
schedule function (step -> lr), evaluated inside the compiled train step.
The reference's ScheduleType.{ITERATION,EPOCH} distinction is expressed by
`steps_per_epoch` at lowering time.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Union

import jax.numpy as jnp

from deeplearning4j_tpu.utils import serde


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base; subclasses define value(step)."""

    def to_fn(self, steps_per_epoch: int = 1):
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedSchedule(Schedule):
    value: float = 1e-3

    def to_fn(self, steps_per_epoch: int = 1):
        v = self.value
        return lambda step: jnp.full((), v, jnp.float32)


@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    """lr * decay_rate ^ floor(t / step)."""

    initial: float = 1e-3
    decay_rate: float = 0.5
    step: float = 1000.0
    per_epoch: bool = False

    def to_fn(self, steps_per_epoch: int = 1):
        unit = self.step * (steps_per_epoch if self.per_epoch else 1.0)
        return lambda t: self.initial * self.decay_rate ** jnp.floor(t / unit)


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 0.999

    def to_fn(self, steps_per_epoch: int = 1):
        return lambda t: self.initial * self.gamma**t


@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    initial: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000

    def to_fn(self, steps_per_epoch: int = 1):
        def fn(t):
            frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
            return self.initial * (1.0 - frac) ** self.power

        return fn


@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 0.01
    step_size: int = 1000

    def to_fn(self, steps_per_epoch: int = 1):
        def fn(t):
            return self.initial / (1.0 + jnp.exp(self.gamma * (t - self.step_size)))

        return fn


@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    initial: float = 1e-3
    gamma: float = 1e-3
    power: float = 1.0

    def to_fn(self, steps_per_epoch: int = 1):
        return lambda t: self.initial / (1.0 + self.gamma * t) ** self.power


@dataclasses.dataclass(frozen=True)
class CosineSchedule(Schedule):
    """Cosine decay with optional linear warmup (the transformer staple)."""

    initial: float = 1e-3
    decay_steps: int = 10000
    warmup_steps: int = 0
    final_fraction: float = 0.0

    def to_fn(self, steps_per_epoch: int = 1):
        def fn(t):
            t = jnp.asarray(t, jnp.float32)
            warm = self.initial * t / max(self.warmup_steps, 1)
            prog = jnp.clip(
                (t - self.warmup_steps) / max(self.decay_steps - self.warmup_steps, 1),
                0.0,
                1.0,
            )
            cos = self.final_fraction + (1 - self.final_fraction) * 0.5 * (
                1 + jnp.cos(math.pi * prog)
            )
            return jnp.where(t < self.warmup_steps, warm, self.initial * cos)

        return fn


for _cls in (FixedSchedule, StepSchedule, ExponentialSchedule, PolySchedule,
             SigmoidSchedule, InverseSchedule, CosineSchedule):
    serde.register(_cls)

ScheduleLike = Union[Schedule, float]


def as_schedule(s: ScheduleLike) -> Schedule:
    return FixedSchedule(float(s)) if isinstance(s, (int, float)) else s
