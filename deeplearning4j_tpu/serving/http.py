"""HTTP frontend for the serving plane — stdlib, like the UIServer.

Every rejection the `InferenceServer` produces maps to an explicit
status code (the docs/serving.md table); an overloaded or degraded
server answers fast with a reason, never hangs the socket:

  POST /v1/infer    {"features": [...], "deadline_ms": 250}
                    -> 200 {"outputs": ..., "latency_ms", "generation"}
                    -> 400 bad request  (malformed JSON / wrong shape)
                    -> 429 queue_full   (backpressure: retry later)
                    -> 503 breaker_open | deadline | admit_fault
                    -> 504 deadline expired after admission
                    -> 500 dispatch failed (wedged / non-finite)
  POST /v1/generate {"prompt": [1, 7, 3], "max_new_tokens": 32,
                     "temperature": 0.8, "top_k": 40, "seed": 0,
                     "stop_tokens": [2], "stream": false,
                     "spec_k": 2}   # optional per-request speculative
                                    # draft length, capped at the
                                    # engine's spec_k (0 = plain decode
                                    # for this stream)
                    -> 200 {"tokens", "prompt_len", "ttft_ms",
                            "generation"}
                    -> 200 (stream=true) newline-delimited JSON chunks
                       {"token", "index"} ... then {"done": true}
                    -> 400 bad request (no engine / over-capacity
                           stream / malformed prompt)
                    -> 429 queue_full | kv_exhausted (retry later)
                    -> 503 breaker_open
                    -> 500 prefill/decode step failed
  POST /v1/reload   {"path": "/ckpts/ckpt_00000042.zip"}
                    -> 200 installed {"generation"}
                    -> 409 rolled_back (verification failed; old params
                           keep serving)
  GET  /healthz     -> 200 serving | 503 breaker open (load balancers
                       pull the replica while it probes recovery);
                       carries the SLO summary (alerting objectives +
                       fast-window burn) when an `observe.slo` engine
                       is installed
  GET  /v1/status   -> 200 stats JSON (queue depth, p50/p99, breaker,
                       swap generation, shed counts, per-request
                       latency_breakdown, slo state; when token
                       generation is enabled, a "generation" block with
                       stream outcomes, tokens/s, the queue/prefill/
                       handoff/decode/sampling breakdown, and flight-
                       recorder counters)

Multi-input graphs POST ``{"inputs": [[...], [...]]}`` — one nested
array per network input.  Features arrive as ONE example (no batch
dim); the server does the batching.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.serving.admission import (
    ServingError, ServingRejected, ServingTimeout,
)

log = logging.getLogger("deeplearning4j_tpu")


def _slo_summary():
    """The active SLO engine's compact summary (None when no engine is
    installed — plain replicas pay nothing).  /healthz is a routing
    decision point, so the engine is SAMPLED on read — the burn rates a
    load balancer sees must be current even if nothing is scraping
    /metrics on this replica."""
    from deeplearning4j_tpu.observe.slo import sample_active_summary

    return sample_active_summary()


def _slo_state():
    from deeplearning4j_tpu.observe.slo import sample_active_state

    return sample_active_state()


class ServingHTTPServer:
    """Thin HTTP shell around an `InferenceServer`."""

    def __init__(self, server, port: int = 0, host: str = "127.0.0.1"):
        self.server = server
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # per-connection socket timeout: a client that sends headers
            # and then dribbles (or never sends) its body must not pin
            # a handler thread forever — bounded admission starts at
            # the socket
            timeout = 30

            def log_message(self, *a):          # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                u = urlparse(self.path)
                if u.path == "/healthz":
                    # the pull-based LB payload (docs/serving.md schema):
                    # shed_pressure / breaker_state / batch_latency_ewma_s
                    # / weights_generation let a router stop sending to
                    # this replica BEFORE it starts shedding
                    health = outer.server.health()
                    health["breaker"] = health["breaker_state"]
                    slo = _slo_summary()
                    if slo is not None:
                        health["slo"] = slo
                    self._json(
                        health,
                        503 if health["status"] == "breaker_open" else 200,
                    )
                elif u.path == "/v1/status":
                    stats = outer.server.stats()
                    engine = getattr(outer.server, "generation_engine",
                                     None)
                    if engine is not None:
                        try:
                            stats["generation"] = engine.stats()
                        except Exception as e:
                            log.debug("status generation join "
                                      "failed: %s", e)
                    slo = _slo_state()
                    if slo is not None:
                        stats["slo"] = slo
                    self._json(stats)
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                u = urlparse(self.path)
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._json({"error": "bad json"}, 400)
                    return
                if u.path == "/v1/infer":
                    self._infer(payload)
                elif u.path == "/v1/generate":
                    self._generate(payload)
                elif u.path == "/v1/reload":
                    self._reload(payload)
                else:
                    self._json({"error": "not found"}, 404)

            def _generate(self, payload):
                engine = getattr(outer.server, "generation_engine", None)
                if engine is None:
                    self._json(
                        {"error": "no generation engine attached to "
                                  "this replica"}, 400)
                    return
                try:
                    prompt = np.asarray(
                        payload.get("prompt"), np.int32).reshape(-1)
                except (TypeError, ValueError) as exc:
                    self._json({"error": f"bad prompt: {exc}"}, 400)
                    return
                kwargs = dict(
                    max_new_tokens=payload.get("max_new_tokens"),
                    temperature=float(payload.get("temperature", 0.0)),
                    top_k=int(payload.get("top_k", 0)),
                    seed=int(payload.get("seed", 0)),
                    stop_tokens=tuple(payload.get("stop_tokens", ())),
                )
                if payload.get("spec_k") is not None:
                    try:
                        kwargs["spec_k"] = int(payload["spec_k"])
                    except (TypeError, ValueError) as exc:
                        self._json({"error": f"bad spec_k: {exc}"}, 400)
                        return
                timeout = float(payload.get("timeout_s", 120.0))
                if payload.get("stream"):
                    self._generate_stream(engine, prompt, kwargs, timeout)
                    return
                try:
                    req = engine.submit(prompt, **kwargs)
                    out = req.result(timeout)
                except ServingRejected as exc:
                    self._json({"error": str(exc), "reason": exc.reason},
                               exc.status)
                    return
                except ServingTimeout as exc:
                    self._json({"error": str(exc),
                                "reason": "deadline_expired"}, exc.status)
                    return
                except ServingError as exc:
                    self._json({"error": str(exc),
                                "reason": "dispatch_failed"}, exc.status)
                    return
                except ValueError as exc:   # over-capacity stream etc.
                    self._json({"error": str(exc)}, 400)
                    return
                self._json({
                    "tokens": np.asarray(out).tolist(),
                    "prompt_len": int(prompt.shape[0]),
                    "ttft_ms": (round(req.ttft_s * 1000.0, 3)
                                if req.ttft_s is not None else None),
                    "generation": outer.server.generation,
                })

            def _generate_stream(self, engine, prompt, kwargs, timeout):
                """Chunked newline-delimited JSON: one {"token", "index"}
                line per generated token as the decode loop emits it,
                then a {"done": true} terminator carrying the totals."""
                import queue as _q

                chunks: _q.Queue = _q.Queue()

                def on_token(tok, idx):
                    chunks.put((tok, idx))

                try:
                    req = engine.submit(prompt, on_token=on_token,
                                        **kwargs)
                except ServingRejected as exc:
                    self._json({"error": str(exc), "reason": exc.reason},
                               exc.status)
                    return
                except ValueError as exc:
                    self._json({"error": str(exc)}, 400)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send(obj):
                    body = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(body):x}\r\n".encode())
                    self.wfile.write(body + b"\r\n")
                    self.wfile.flush()

                import time as _t

                t_end = _t.monotonic() + timeout
                try:
                    while True:
                        try:
                            tok, idx = chunks.get(timeout=0.1)
                            send({"token": int(tok), "index": int(idx)})
                        except _q.Empty:
                            if req.done and chunks.empty():
                                break
                            if _t.monotonic() > t_end:
                                req.cancel()
                                break
                    err = req.error
                    send({"done": True,
                          "n_tokens": len(req.tokens_so_far()),
                          "error": str(err) if err is not None else None,
                          "ttft_ms": (round(req.ttft_s * 1000.0, 3)
                                      if req.ttft_s is not None
                                      else None)})
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # client hung up mid-stream: stop decoding for them
                    req.cancel()

            def _infer(self, payload):
                try:
                    if "inputs" in payload:
                        feats = tuple(
                            np.asarray(a, np.float32)
                            for a in payload["inputs"]
                        )
                    else:
                        feats = np.asarray(
                            payload.get("features"), np.float32,
                        )
                    deadline_ms = payload.get("deadline_ms")
                    deadline_s = (
                        float(deadline_ms) / 1000.0
                        if deadline_ms is not None else None
                    )
                except (TypeError, ValueError) as exc:
                    self._json({"error": f"bad features: {exc}"}, 400)
                    return
                import time

                t0 = time.monotonic()
                try:
                    req = outer.server.submit(feats, deadline_s=deadline_s)
                    result = req.result()
                except ServingRejected as exc:
                    self._json(
                        {"error": str(exc), "reason": exc.reason},
                        exc.status,
                    )
                    return
                except ServingTimeout as exc:
                    self._json({"error": str(exc),
                                "reason": "deadline_expired"}, exc.status)
                    return
                except ServingError as exc:
                    self._json({"error": str(exc),
                                "reason": "dispatch_failed"}, exc.status)
                    return
                except ValueError as exc:      # wrong arity/shape
                    self._json({"error": str(exc)}, 400)
                    return
                outs = (
                    [np.asarray(o).tolist() for o in result]
                    if isinstance(result, tuple)
                    else np.asarray(result).tolist()
                )
                self._json({
                    "outputs": outs,
                    "latency_ms": round(
                        (time.monotonic() - t0) * 1000.0, 3,
                    ),
                    "generation": outer.server.generation,
                })

            def _reload(self, payload):
                path = payload.get("path")
                if not path:
                    self._json({"error": "missing 'path'"}, 400)
                    return
                if outer.server.push_checkpoint(path):
                    self._json({"installed": True,
                                "generation": outer.server.generation})
                else:
                    self._json(
                        {"installed": False,
                         "error": "verification failed; previous "
                                  "weights keep serving"},
                        409,
                    )

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}/"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServingHTTPServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="dl4jtpu-serving-http",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
