"""Serving flight recorder — post-mortem dumps for the generation plane.

`runtime/crash.py` answers "why did the TRAINING step hang" with a
hang report written at abort time; nothing answered the serving twin:
"what was every recent stream doing when the decode plane went bad?".
This module is that answer.  The engine appends one bounded record per
settled stream (timings breakdown, KV pages held, outcome, trace id),
and the ring is snapshotted to a JSON dump whenever one of four
triggers fires:

- ``watchdog_abort``  — the decode watchdog aborted a wedged dispatch
- ``breaker_open``    — the shared circuit breaker tripped open
- ``kv_exhausted_spike`` — KV-pool 429s clustered inside a short window
- ``slo_alert``       — a burn-rate alert crossed its rising edge
  (wired via `observe.slo.add_alert_listener`; observe/ never imports
  serving/)

Dumps land next to hang reports (``DL4JTPU_CRASH_DIR``, default cwd)
as ``dl4jtpu-flight-record-<ms>-<seq>.json`` with schema
``dl4jtpu-flight-record/1``: trigger, trigger context, the per-stream
records, and whatever engine/KV state the caller attaches.  Per-trigger
cooldowns keep a flapping breaker from filling the disk; every write
is best-effort — the recorder must never take the serving plane down
with it.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Optional

from deeplearning4j_tpu.runtime.crash import ENV_CRASH_DIR

log = logging.getLogger("deeplearning4j_tpu")

#: settled-stream records retained (oldest evicted first)
FLIGHT_RING_CAP = 256
#: trailing window (s) over which KV-exhaustion 429s count as a spike
KV_SPIKE_WINDOW_S = 5.0
#: 429s inside the window that constitute a spike
KV_SPIKE_THRESHOLD = 3
#: default per-trigger dump cooldown (s)
DUMP_COOLDOWN_S = 30.0

_dump_seq = itertools.count()


class FlightRecorder:
    """Bounded ring of per-stream records + triggered JSON dumps.

    Thread-safe: `record`/`note_kv_exhausted` run on the decode loop,
    `dump` can arrive from the watchdog monitor thread or an SLO
    evaluation tick concurrently.
    """

    def __init__(self, capacity: int = FLIGHT_RING_CAP,
                 cooldown_s: float = DUMP_COOLDOWN_S,
                 spike_window_s: float = KV_SPIKE_WINDOW_S,
                 spike_threshold: int = KV_SPIKE_THRESHOLD):
        self._records: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.cooldown_s = cooldown_s
        self.spike_window_s = spike_window_s
        self.spike_threshold = max(1, int(spike_threshold))
        self._rejects: deque = deque(maxlen=64)   # 429 timestamps
        self._last_dump: dict = {}                # trigger -> monotonic t
        self.dumps_written = 0
        self.dump_paths: list = []
        #: callable returning extra context merged into every dump
        #: (the owning engine attaches its stats/KV snapshot here)
        self.context_fn: Optional[Callable[[], dict]] = None
        self._slo_listener = None

    # -- the ring ------------------------------------------------------------
    def record(self, rec: dict) -> None:
        """Append one settled-stream record (oldest evicted at cap)."""
        with self._lock:
            self._records.append(rec)
            n = len(self._records)
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().gauge("dl4jtpu_flight_records").set(float(n))
        except Exception as e:
            log.debug("flight ring gauge failed: %s", e)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # -- triggers ------------------------------------------------------------
    def note_kv_exhausted(self) -> Optional[str]:
        """Count one KV-pool 429; dump when they cluster (>= threshold
        inside the trailing spike window).  Returns the dump path when
        a spike fired."""
        now = time.monotonic()
        with self._lock:
            self._rejects.append(now)
            cutoff = now - self.spike_window_s
            recent = sum(1 for t in self._rejects if t >= cutoff)
        if recent >= self.spike_threshold:
            return self.dump("kv_exhausted_spike",
                             context={"rejects_in_window": recent,
                                      "window_s": self.spike_window_s})
        return None

    def dump(self, trigger: str, context: Optional[dict] = None,
             path: Optional[str] = None, force: bool = False,
             ) -> Optional[str]:
        """Snapshot the ring to a post-mortem JSON file.  Per-trigger
        cooldown unless `force`; returns the path, or None when on
        cooldown or the write failed (best-effort by contract)."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(trigger)
            if (not force and last is not None
                    and now - last < self.cooldown_s):
                return None
            self._last_dump[trigger] = now
            records = list(self._records)
        doc = {
            "schema": "dl4jtpu-flight-record/1",
            "trigger": trigger,
            "time": time.time(),
            "context": context or {},
            "records": records,
        }
        try:
            if self.context_fn is not None:
                doc["engine"] = self.context_fn()
        except Exception as e:
            doc["engine"] = {"error": str(e)}
        try:
            from deeplearning4j_tpu.observe.slo import active_engine

            eng = active_engine()
            if eng is not None:
                doc["slo"] = eng.state()      # last tick, no resample
        except Exception as e:
            log.debug("flight dump slo join failed: %s", e)
        if path is None:
            path = os.path.join(
                os.environ.get(ENV_CRASH_DIR, "."),
                f"dl4jtpu-flight-record-{int(time.time() * 1000)}"
                f"-{next(_dump_seq)}.json",
            )
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, default=str)
            os.replace(tmp, path)
        except Exception as e:
            log.warning("flight-recorder dump failed: %s", e)
            return None
        with self._lock:
            self.dumps_written += 1
            self.dump_paths.append(path)
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().counter("dl4jtpu_flight_dumps_total").inc(
                trigger=trigger)
        except Exception as e:
            log.debug("flight dump counter failed: %s", e)
        log.warning("flight recorder dumped %d stream records to %s "
                    "(trigger=%s)", len(records), path, trigger)
        return path

    # -- SLO wiring ----------------------------------------------------------
    def attach_slo_trigger(self) -> None:
        """Register a process-wide rising-edge listener that dumps this
        ring on any SLO alert.  Holds only a weakref to the recorder;
        `detach_slo_trigger` (or recorder GC) unhooks it."""
        from deeplearning4j_tpu.observe import slo

        if self._slo_listener is not None:
            return
        ref = weakref.ref(self)

        def _on_alert(name: str, state: dict) -> None:
            rec = ref()
            if rec is None:
                slo.remove_alert_listener(_on_alert)
                return
            rec.dump("slo_alert",
                     context={"objective": name, "state": state})

        self._slo_listener = _on_alert
        slo.add_alert_listener(_on_alert)

    def detach_slo_trigger(self) -> None:
        if self._slo_listener is None:
            return
        from deeplearning4j_tpu.observe import slo

        slo.remove_alert_listener(self._slo_listener)
        self._slo_listener = None
