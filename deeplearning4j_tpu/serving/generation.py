"""Token-level continuous-batching generation serving.

`ops/generation.py` decodes ONE prompt against a dense per-request
cache — the right reference semantics, the wrong serving shape: a
request-at-a-time `generate()` leaves the device idle for every other
stream while one stream decodes, and its dense cache reserves
O(prompt + max_new) HBM per request up front.  This module lifts that
loop into the serving plane the way the Gemma-on-TPU serving stack does:

- **one jitted decode step, fixed slot batch** — `GenerationEngine`
  advances `slots` sequences ONE token per dispatch.  Shapes are static
  (slot count, page-table width), so the whole serving life of the
  engine is a single compiled program; requests join and leave the
  running batch BETWEEN steps, never inside one (continuous batching at
  token granularity, not request granularity).
- **paged KV** — K/V live in `serving/kv_cache.py` pool pages indexed
  by per-slot page tables; `ops/paged_attention.py` attends one query
  row per slot against them.  An idle slot points every table entry at
  the pool's scratch page and carries ``seq_len 0`` — it rides the same
  program as live slots and contributes garbage that nobody reads.
- **bucketed prefill** — the prompt runs as a separate program per
  `flags.bucket_length` bucket (bucket quantum = a page-size multiple,
  so prompt KV lands page-aligned), emits the first token (that is the
  TTFT moment) and hands its K/V rows to the pool.  `prefill_detached`
  / `join_prefilled` split that handoff across replicas — the
  prefill/decode disaggregation seam `ServingFleet.generate` routes.
- **the ladder still holds** — admission is a bounded queue (429 when
  full), KV-pool exhaustion is an explicit ``kv_exhausted`` 429 (never
  a silent stall), each decode step runs under a `StepWatchdog` whose
  abort fails every in-flight stream AND releases all their pages, the
  shared breaker trips on step failures, and a hot-swap lands between
  decode steps (the step snapshots params under the server's weights
  lock) so in-flight streams finish — on the new weights — with zero
  drops.

Numerics contract: greedy paged decode is token-identical to
`ops.generation.generate` for f32 (same per-position math, same
`fold_in` RNG schedule, same top-k threshold rule), and int8-KV pages
are gated by agreement the way PR 13 gated PTQ parity.

Observability (docs/observability.md "Generation plane"): every stream
settles through ONE fate point (`_finish`), which records the
``generation.stream`` root span exactly once, bumps the per-outcome
stream counter, observes the six-segment latency breakdown
(queue / prefill / handoff / decode_queue / decode_compute / sampling),
offers the stream to the slowest-streams exemplar ring
(``GET /api/generation/slow``), and appends a flight-recorder record —
so watchdog-aborted, KV-exhausted (429) and client-cancelled streams
get the same complete causal chain as happy ones, per the PR 12
contract.  Span taxonomy per stream: ``generation.admit`` (enqueue to
taken) -> ``generation.prefill`` (bucketed prompt forward, wherever it
ran) -> ``generation.kv_handoff`` (prefill K/V landing in the decode
pool; cross-replica it starts at the prefill replica's completion
mark) -> one ``generation.decode_step`` span per step per co-resident
stream (args carry the batch composition: co-resident rids and
per-stream token counts) -> the ``generation.stream`` root.  The trace
context rides the `prefill_detached` handoff dict, so a disaggregated
stream is one causal chain across replicas on ``/api/trace/cluster``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observe import trace as otrace
from deeplearning4j_tpu.ops.generation import (
    _block_prefill,
    _head_logits,
    _ln,
    _pe_row,
    _plan,
)
from deeplearning4j_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_chunk,
)
from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.runtime.flags import bucket_length
from deeplearning4j_tpu.runtime.watchdog import StepWatchdog
from deeplearning4j_tpu.serving.admission import (
    AdmissionQueue,
    ServingError,
    ServingRejected,
    ServingTimeout,
)
from deeplearning4j_tpu.serving.flight import FlightRecorder
from deeplearning4j_tpu.serving.kv_cache import (
    SCRATCH_PAGE,
    KVPoolExhausted,
    PagedKVCache,
    quantize_page_rows,
)
from deeplearning4j_tpu.serving import speculative

log = logging.getLogger("deeplearning4j_tpu")

#: slowest-stream exemplars kept per engine (the serving twin of
#: server.SLOW_RING_CAP — bounded, readable mid-incident)
GEN_SLOW_RING_CAP = 16

#: the per-stream latency segments, in lifecycle order (breakdown dict
#: keys, histogram families and docs share this vocabulary);
#: decode_queue is the residual: slot residency not spent in decode
#: compute or sampling
GEN_BREAKDOWN_SEGMENTS = ("queue", "prefill", "handoff", "decode_queue",
                          "decode_compute", "sampling")

_GEN_BREAKDOWN_FAMILIES = None


def _gen_breakdown_families() -> dict:
    """Segment-name -> histogram, resolved once — per-stream
    attribution must not pay registry lookups/locks."""
    global _GEN_BREAKDOWN_FAMILIES
    if _GEN_BREAKDOWN_FAMILIES is None:
        from deeplearning4j_tpu.observe.metrics import registry

        reg = registry()
        _GEN_BREAKDOWN_FAMILIES = {
            seg: reg.histogram(f"dl4jtpu_generation_{seg}_seconds")
            for seg in GEN_BREAKDOWN_SEGMENTS
        }
    return _GEN_BREAKDOWN_FAMILIES


@dataclass
class GenerationConfig:
    """Engine knobs.  ``slots`` and ``max_pages_per_seq`` are STATIC
    shape parameters of the one decode program; ``page_size`` times
    ``max_pages_per_seq`` bounds a stream's total length (prompt bucket
    plus generated tokens)."""

    slots: int = 8                 # decode batch width (static)
    page_size: int = 16            # KV page rows (bucket_length-quantized)
    num_pages: int = 128           # pool size (page 0 is scratch)
    max_pages_per_seq: int = 8     # page-table width (static)
    kv_dtype: str = "f32"          # f32 | int8 pages
    prefill_quantum: Optional[int] = None   # default: page_size
    max_queue: int = 128
    default_max_new: int = 32
    attention_impl: Optional[str] = None    # force pallas|xla (None = auto)
    attention_interpret: Optional[bool] = None
    watchdog_floor_s: float = 30.0
    watchdog_cold_floor_s: float = 600.0
    watchdog_k: float = 10.0
    poll_s: float = 0.02           # idle-queue poll granularity
    # speculative decoding (serving/speculative.py): draft length per
    # stream per step (0 = off; None = DL4J_TPU_SPEC_K), the drafter
    # (None = DL4J_TPU_SPEC_DRAFTER, default "ngram"), and the small
    # zoo model the "model" drafter decodes with
    spec_k: Optional[int] = None
    spec_drafter: Optional[str] = None
    spec_draft_model: object = None


class GenerationRequest:
    """One admitted stream: prompt, sampling params, stop conditions,
    and the token sink the decode loop appends into.  The client waits
    on `result()`; streaming readers poll `tokens_so_far()` or get
    ``on_token(token, index)`` callbacks from the engine thread."""

    __slots__ = ("rid", "prompt", "max_new", "temperature", "top_k",
                 "seed", "stop_tokens", "on_token", "tokens", "error",
                 "cancelled", "prefilled", "signature", "seq",
                 "t_submit", "ttft_s", "_event", "_lock",
                 # observability riders (engine-written; see _finish):
                 # trace linkage, latency-segment dict, fate bookkeeping
                 "trace_id", "root_span", "root_parent", "lat",
                 "outcome", "trace_done", "t_offer", "t_slot", "pages",
                 # speculative decode: per-request draft-length override
                 # (None = engine default, 0 = off for this stream),
                 # the mid-stream fallback latch, and acceptance counts
                 "spec_k", "spec_disabled", "spec_drafted",
                 "spec_accepted")

    _next = [0]

    def __init__(self, prompt: np.ndarray, max_new: int, *,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 stop_tokens: tuple = (), on_token=None, prefilled=None,
                 spec_k: Optional[int] = None):
        GenerationRequest._next[0] += 1
        self.rid = f"gen-{GenerationRequest._next[0]}"
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.stop_tokens = tuple(int(t) for t in stop_tokens)
        self.on_token = on_token
        self.prefilled = prefilled     # disaggregation handoff dict
        self.tokens: list[int] = []
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self.signature = ("generate",)  # AdmissionQueue grouping key
        self.seq = 0
        self.t_submit = time.perf_counter()
        self.ttft_s: Optional[float] = None
        self.trace_id: Optional[int] = None
        self.root_span: Optional[int] = None
        self.root_parent: Optional[int] = None
        self.lat: dict = {}            # segment -> seconds (see _finish)
        self.outcome: Optional[str] = None
        self.trace_done = False        # fate settled exactly once
        self.t_offer: Optional[float] = None
        self.t_slot: Optional[float] = None
        self.pages = 0                 # KV pages held at admission
        self.spec_k = None if spec_k is None else max(0, int(spec_k))
        self.spec_disabled = False
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._event = threading.Event()
        self._lock = threading.Lock()

    # -- engine side -------------------------------------------------------
    def _record(self, token: int) -> None:
        with self._lock:
            if self.ttft_s is None:
                self.ttft_s = time.perf_counter() - self.t_submit
            self.tokens.append(int(token))
            idx = len(self.tokens) - 1
        if self.on_token is not None:
            try:
                self.on_token(int(token), idx)
            except Exception:
                log.exception("on_token callback raised")

    def _complete(self) -> None:
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self.error = exc
        self._event.set()

    # -- client side -------------------------------------------------------
    def tokens_so_far(self) -> list[int]:
        with self._lock:
            return list(self.tokens)

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> None:
        self.cancelled = True

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for completion; returns prompt + generated tokens
        (the `ops.generation.generate` row shape)."""
        if not self._event.wait(timeout):
            self.cancelled = True
            raise ServingTimeout(
                f"generation {self.rid} incomplete after {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens_so_far(), np.int32)]
        )


def _sample_token(logits, temp, top_k, key):
    """`ops.generation._sample` with RUNTIME sampling params, for one
    (V,) logits row — temperature/top_k ride the batch as traced
    per-slot scalars so the sampling config never recompiles the step.
    The kth-largest threshold (descending sort at [k-1]) is the exact
    value `lax.top_k(x, k)[0][..., -1]` gives the dense reference, and
    greedy argmaxes the UNSCALED logits exactly like the reference's
    ``temperature <= 0`` branch."""
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    greedy = jnp.argmax(logits).astype(jnp.int32)
    t = jnp.where(temp > 0.0, temp, 1.0)
    scaled = logits / t
    order = jnp.sort(scaled)[::-1]
    kth = jnp.where(top_k > 0, order[jnp.clip(top_k - 1, 0, v - 1)],
                    -jnp.inf)
    masked = jnp.where(scaled < kth, -jnp.inf, scaled)
    samp = jax.random.categorical(key, masked).astype(jnp.int32)
    return jnp.where(temp <= 0.0, greedy, samp)


def _slot_keys(seeds, gen_counts):
    """Per-slot sampling keys on the dense reference's schedule: the
    g-th generated token of a stream seeded ``s`` uses
    ``fold_in(key(s), g)`` (the reference samples its first token with
    ``fold_in(rng, 0)`` and tick ``i`` with ``fold_in(rng, i + 1)``)."""
    return jax.vmap(
        lambda s, g: jax.random.fold_in(jax.random.key(s), g)
    )(seeds, gen_counts)


class GenerationEngine:
    """Continuous-batching decode engine over a paged KV pool.

        engine = GenerationEngine(model=m, config=GenerationConfig())
        engine.start()
        req = engine.submit(prompt_ids, max_new_tokens=32)
        out = req.result(timeout=30)        # prompt + generated tokens

    Attach to an `InferenceServer` (``server=``) to ride its ladder:
    params snapshot under the server's weights lock (hot-swap lands
    between decode steps), step failures feed the shared breaker,
    admission honors breaker state, and `server.shed_pressure` folds in
    KV-pool occupancy.  Standalone (``model=``) runs the same engine
    with its own lock for tests and benchmarks.
    """

    def __init__(self, model=None, server=None,
                 config: Optional[GenerationConfig] = None):
        if (model is None) == (server is None):
            raise ValueError("pass exactly one of model= or server=")
        self.server = server
        self.model = server.model if server is not None else model
        if self.model.params is None:
            self.model.init()
        self.config = cfg = config or GenerationConfig()
        self._weights_lock = (
            server._weights_lock if server is not None else threading.Lock()
        )
        self.breaker = server.breaker if server is not None else None

        embed, pos, blocks, head = _plan(self.model)
        self._stack = (embed, pos, tuple(blocks), head)
        names = [l.name for l in self.model.conf.layers]
        self._embed_name, self._head_name = names[0], names[-1]
        self._pos_name = pos.name if pos is not None else None
        self._block_names = [b.name for b in blocks]
        self._d = embed.n_out
        self._n_heads = blocks[0].n_heads
        self._head_dim = blocks[0].d_model // blocks[0].n_heads

        self.kv = PagedKVCache(
            n_layers=len(blocks), n_heads=self._n_heads,
            head_dim=self._head_dim, num_pages=cfg.num_pages,
            page_size=cfg.page_size, kv_dtype=cfg.kv_dtype,
        )
        self._quantum = cfg.prefill_quantum or self.kv.page_size
        if self._quantum % self.kv.page_size:
            raise ValueError(
                f"prefill_quantum {self._quantum} must be a multiple of "
                f"the page size {self.kv.page_size} (prompt KV must land "
                "page-aligned)"
            )

        s, mp = cfg.slots, cfg.max_pages_per_seq
        # host slot state; the decode step consumes these by value, so
        # mutating them BETWEEN steps is the continuous-batching join
        self._page_tbl = np.full((s, mp), SCRATCH_PAGE, np.int32)
        self._seq_lens = np.zeros(s, np.int32)
        self._last_tok = np.zeros(s, np.int32)
        self._gen_counts = np.zeros(s, np.int32)
        self._temps = np.zeros(s, np.float32)
        self._top_ks = np.zeros(s, np.int32)
        self._seeds = np.zeros(s, np.uint32)
        self._slot_req: list[Optional[GenerationRequest]] = [None] * s

        self.queue = AdmissionQueue(cfg.max_queue)
        self._mu = threading.Lock()       # slot state + loop generation
        self._stop = threading.Event()
        self._loop_gen = 0
        self._thread: Optional[threading.Thread] = None
        self.watchdog = StepWatchdog(
            floor_s=cfg.watchdog_floor_s,
            cold_floor_s=cfg.watchdog_cold_floor_s,
            k=cfg.watchdog_k, abort=self._on_wedged, name="generation",
        )
        self._steps = 0
        self._tokens_out = 0
        self._step_fn = None
        self._prefill_fns: dict[int, Callable] = {}
        # speculative decode: resolve the engine-wide draft length and
        # drafter once (env knobs DL4J_TPU_SPEC_K/DL4J_TPU_SPEC_DRAFTER,
        # overridden by explicit config fields); spec_k == 0 keeps the
        # whole path disabled and the verify program never built
        k = (cfg.spec_k if cfg.spec_k is not None
             else speculative.spec_k_from_env(0))
        self.spec_k = max(0, int(k))
        self.drafter: Optional[speculative.DraftSource] = None
        if self.spec_k > 0:
            self.drafter = speculative.make_drafter(
                cfg.spec_drafter or speculative.drafter_from_env(),
                draft_model=cfg.spec_draft_model,
            )
        self._verify_fn = None
        self._vocab = int(
            self.model.params[self._embed_name]["W"].shape[0])
        self._spec_counts = {"drafted": 0, "accepted": 0, "rejected": 0,
                             "bonus": 0, "emitted": 0,
                             "verify_dispatches": 0,
                             "plain_dispatches": 0, "fallbacks": 0}
        # observability: trace recorder handle, slow-stream exemplar
        # ring, breakdown totals, and the flight recorder with its
        # SLO-alert rising-edge trigger (detached at stop())
        self._rec = otrace.tracer()
        self._stats_lock = threading.Lock()
        self._slow: list[dict] = []
        self._lat_totals = {k: 0.0 for k in GEN_BREAKDOWN_SEGMENTS}
        self._stream_outcomes: dict[str, int] = {}
        self._streams_settled = 0
        self._rate_samples: deque = deque(maxlen=64)  # (t, tokens_out)
        self.flight = FlightRecorder()
        self.flight.context_fn = self._flight_context
        self.flight.attach_slo_trigger()
        if server is not None:
            server.generation_engine = self

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "GenerationEngine":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        with self._mu:
            self._loop_gen += 1
            gen = self._loop_gen
        self._thread = threading.Thread(
            target=self._loop, args=(gen,),
            name="dl4jtpu-generation", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        for req in self.queue.drain():
            self._finish(req, "shutdown",
                         ServingRejected("shutdown", "engine stopped"))
        with self._mu:
            self._fail_active_locked(
                ServingRejected("shutdown", "engine stopped"),
                outcome="shutdown",
            )
        self.flight.detach_slo_trigger()

    # -- admission ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None, *,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               stop_tokens: tuple = (), on_token=None,
               trace_ctx=None, spec_k: Optional[int] = None,
               ) -> GenerationRequest:
        """Admit one stream.  Raises `ServingRejected` on a full queue
        or an open breaker; over-capacity streams (longer than the page
        table can hold) are client errors (`ValueError`).  `trace_ctx`
        is an upstream ``(trace_id, root_span)`` pair (the fleet's
        routed path allocates one so the router pick joins the stream
        chain); None allocates fresh ids when tracing is on.  `spec_k`
        overrides the engine's speculative draft length for THIS stream
        (0 = plain decode; capped at the engine's configured k — the
        verify program's chunk width is static)."""
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.config.default_max_new)
        req = GenerationRequest(
            prompt, max_new, temperature=temperature, top_k=top_k,
            seed=seed, stop_tokens=stop_tokens, on_token=on_token,
            spec_k=spec_k,
        )
        self._validate(req)
        self._init_trace(req, trace_ctx)
        self._offer_counted(req)
        return req

    def _validate(self, req: GenerationRequest) -> None:
        t_p = req.prompt.shape[0]
        if t_p < 1:
            raise ValueError("empty prompt")
        if req.max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        span = max(bucket_length(t_p, self._quantum), t_p + req.max_new)
        if self.kv.pages_for(span) > self.config.max_pages_per_seq:
            cap = self.config.max_pages_per_seq * self.kv.page_size
            raise ValueError(
                f"stream needs {span} KV positions; the page table holds "
                f"{cap} (max_pages_per_seq x page_size)"
            )
        _, pos, _, _ = self._stack
        if pos is not None and pos.learned and span > pos.max_length:
            raise ValueError(
                f"stream needs {span} positions; learned "
                f"PositionalEncoding max_length is {pos.max_length}"
            )

    def _offer(self, req: GenerationRequest) -> None:
        if self.breaker is not None and not self.breaker.admits():
            raise ServingRejected(
                "breaker_open", f"circuit breaker is {self.breaker.state}"
            )
        if not self.queue.offer(req):
            raise ServingRejected(
                "queue_full",
                f"generation queue at capacity ({self.queue.max_queue})",
            )

    def _offer_counted(self, req: GenerationRequest) -> None:
        """Offer + admission bookkeeping: a synchronous reject is
        counted as a stream outcome (its reason), an accepted stream
        bumps the demand counter behind throughput SLOs and stamps the
        enqueue mark the queue segment reads."""
        try:
            self._offer(req)
        except ServingRejected as exc:
            self._count_stream(exc.reason)
            raise
        req.t_offer = time.perf_counter()
        self._count_admitted()

    def _init_trace(self, req: GenerationRequest, trace_ctx=None) -> None:
        """Allocate (or adopt) the stream's trace linkage BEFORE the
        queue sees it — same contract as server._admit.  No-op when
        tracing is off: untraced streams still get breakdowns."""
        if not self._rec.enabled:
            return
        if trace_ctx is not None:
            req.trace_id, req.root_span = trace_ctx
        else:
            req.trace_id = otrace.next_id()
            req.root_span = otrace.next_id()

    def _trace_segment(self, req: GenerationRequest, name: str,
                       t0_pc: float, dur: float, **args) -> None:
        """One child span of the stream's root chain (no-op untraced)."""
        if req.trace_id is None or not self._rec.enabled:
            return
        self._rec.add_complete(
            name, t0_pc, dur, cat="generation",
            **otrace.trace_args(req.trace_id, otrace.next_id(),
                                req.root_span),
            **args,
        )

    def generate(self, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 stop_tokens: tuple = (),
                 timeout: Optional[float] = 120.0) -> np.ndarray:
        """Blocking convenience wrapper — submit one stream, wait, and
        return the `ops.generation.generate`-shaped row."""
        return self.submit(
            prompt, max_new_tokens, temperature=temperature, top_k=top_k,
            seed=seed, stop_tokens=stop_tokens,
        ).result(timeout)

    # -- prefill/decode disaggregation ------------------------------------
    def prefill_detached(self, prompt, max_new_tokens: int, *,
                         temperature: float = 0.0, top_k: int = 0,
                         seed: int = 0, stop_tokens: tuple = (),
                         trace_ctx=None, spec_k: Optional[int] = None,
                         ) -> dict:
        """Run ONLY the prefill program here and return a portable
        handoff (prompt K/V rows as host arrays + the first token + the
        stream's sampling state).  A decode-role replica resumes the
        stream from it via `join_prefilled` — K/V cross the replica
        boundary in f32 and land in whatever page dtype the DECODE
        pool uses, so a f32 prefill replica can feed an int8 decode
        replica.  The stream's trace context (adopted from `trace_ctx`
        or allocated here) and timing marks ride the handoff, so the
        decode replica extends the SAME causal chain."""
        req = GenerationRequest(
            prompt, int(max_new_tokens), temperature=temperature,
            top_k=top_k, seed=seed, stop_tokens=stop_tokens,
        )
        self._validate(req)
        self._init_trace(req, trace_ctx)
        try:
            faults.maybe_fail("serving.prefill")
        except Exception as exc:
            raise ServingError(f"injected prefill fault: {exc}") from exc
        t_pre0 = time.perf_counter()
        k, v, first, ttft_anchor = self._run_prefill(req)
        pre_s = time.perf_counter() - t_pre0
        self._trace_segment(req, "generation.prefill", t_pre0, pre_s,
                            bucket=int(k.shape[1]), detached=True)
        out = {
            "prompt": req.prompt, "k": np.asarray(k), "v": np.asarray(v),
            "first_token": int(first), "max_new": req.max_new,
            "temperature": req.temperature, "top_k": req.top_k,
            "seed": req.seed, "stop_tokens": req.stop_tokens,
            "t_submit": ttft_anchor,
            "prefill_s": pre_s,
            "t_done_pc": time.perf_counter(),
        }
        if spec_k is not None:
            out["spec_k"] = max(0, int(spec_k))
        if req.trace_id is not None:
            out["trace"] = (req.trace_id, req.root_span)
        return out

    def join_prefilled(self, handoff: dict,
                       on_token=None) -> GenerationRequest:
        """Admit a stream whose prefill already ran elsewhere (the
        decode side of the disaggregation seam).  Adopts the handoff's
        trace context — the root span settles HERE, where the stream's
        fate is decided — and its prefill timing for the breakdown."""
        req = GenerationRequest(
            handoff["prompt"], handoff["max_new"],
            temperature=handoff["temperature"], top_k=handoff["top_k"],
            seed=handoff["seed"], stop_tokens=handoff["stop_tokens"],
            on_token=on_token, prefilled=handoff,
            spec_k=handoff.get("spec_k"),
        )
        req.t_submit = handoff.get("t_submit", req.t_submit)
        self._validate(req)
        self._init_trace(req, handoff.get("trace"))
        if "prefill_s" in handoff:
            req.lat["prefill"] = float(handoff["prefill_s"])
        self._offer_counted(req)
        return req

    # -- compiled programs -------------------------------------------------
    def _make_prefill(self, t_b: int):
        embed, pos, blocks, head = self._stack
        pos_name, head_name = self._pos_name, self._head_name
        block_names, embed_name = self._block_names, self._embed_name
        dt = jnp.bfloat16 if self.model._bf16 else jnp.float32

        @jax.jit
        def prefill(params, prompt_pad, prompt_len, seed, temp, top_k):
            # prompt_pad: (1, t_b); rows past prompt_len are pad — with
            # causal attention they influence nothing before them, and
            # their garbage K/V rows sit beyond seq_len (masked at
            # decode, overwritten as the stream grows into them)
            E = params[embed_name]["W"].astype(dt)
            x = embed._act()(E[prompt_pad])
            if pos is not None:
                x, _ = pos.apply(params.get(pos_name, {}), {}, x)
            ks, vs = [], []
            for cfg_b, nm in zip(blocks, block_names):
                x, k, v = _block_prefill(cfg_b, params[nm], x, None)
                ks.append(k[0])
                vs.append(v[0])
            h_last = x[0, prompt_len - 1]
            logits = _head_logits(head, params[head_name], h_last)
            first = _sample_token(
                logits, temp, top_k,
                jax.random.fold_in(jax.random.key(seed), 0),
            )
            return (jnp.stack(ks).astype(jnp.float32),
                    jnp.stack(vs).astype(jnp.float32), first)

        return prefill

    def _prefill_fn(self, t_b: int):
        # `jax.jit` construction is lazy (compilation happens at the
        # first CALL, outside this lock), so memoizing under `_mu` is
        # cheap even with the decode loop live
        with self._mu:
            fn = self._prefill_fns.get(t_b)
            if fn is None:
                fn = self._prefill_fns[t_b] = self._make_prefill(t_b)
        return fn

    def _run_prefill(self, req: GenerationRequest):
        """Dispatch the bucketed prefill program for one request;
        returns (k, v, first_token, ttft_anchor) with k/v shaped
        (n_layers, t_bucket, H, Dh) f32."""
        t_p = req.prompt.shape[0]
        t_b = bucket_length(t_p, self._quantum)
        pad = np.zeros((1, t_b), np.int32)
        pad[0, :t_p] = req.prompt
        with self._weights_lock:
            params = self.model.params
        k, v, first = self._prefill_fn(t_b)(
            params, pad, np.int32(t_p), np.uint32(req.seed),
            np.float32(req.temperature), np.int32(req.top_k),
        )
        return k, v, int(first), req.t_submit

    def _make_step(self):
        embed, pos, blocks, head = self._stack
        pos_name, head_name = self._pos_name, self._head_name
        block_names, embed_name = self._block_names, self._embed_name
        d, ps = self._d, self.kv.page_size
        h_, dh = self._n_heads, self._head_dim
        quant = self.kv.kv_dtype == "int8"
        impl = self.config.attention_impl
        interp = self.config.attention_interpret
        n_slots = self.config.slots

        @jax.jit
        def step(params, k_pages, v_pages, k_scales, v_scales,
                 page_tbl, seq_lens, last_tok, seeds, gen_counts,
                 temps, top_ks):
            dt = jnp.bfloat16 if self.model._bf16 else jnp.float32
            active = seq_lens > 0
            pos_idx = seq_lens                       # write position
            E = params[embed_name]["W"].astype(dt)
            x_t = embed._act()(E[last_tok])          # (S, D)
            pe = jax.vmap(
                lambda t: _pe_row(pos, params.get(pos_name, {}), t, d)
            )(pos_idx)
            x_t = x_t + pe.astype(dt)
            page_of = page_tbl[jnp.arange(n_slots), pos_idx // ps]
            row_of = pos_idx % ps
            attend = seq_lens + 1                    # includes this token
            for li, (cfg_b, nm) in enumerate(zip(blocks, block_names)):
                lp = params[nm]
                ap = lp["attn"]
                hh = _ln(lp["ln1"], x_t)
                q = (hh @ ap["Wq"].astype(dt)).reshape(n_slots, h_, dh)
                k_t = (hh @ ap["Wk"].astype(dt)).reshape(n_slots, h_, dh)
                v_t = (hh @ ap["Wv"].astype(dt)).reshape(n_slots, h_, dh)
                if quant:
                    kq, ksc = quantize_page_rows(k_t)
                    vq, vsc = quantize_page_rows(v_t)
                    k_pages = k_pages.at[li, page_of, row_of].set(kq)
                    v_pages = v_pages.at[li, page_of, row_of].set(vq)
                    k_scales = k_scales.at[li, page_of, row_of].set(ksc)
                    v_scales = v_scales.at[li, page_of, row_of].set(vsc)
                    attn = paged_attention(
                        q.astype(jnp.float32), k_pages[li], v_pages[li],
                        page_tbl, attend, k_scale=k_scales[li],
                        v_scale=v_scales[li], impl=impl, interpret=interp,
                    )
                else:
                    k_pages = k_pages.at[li, page_of, row_of].set(
                        k_t.astype(k_pages.dtype))
                    v_pages = v_pages.at[li, page_of, row_of].set(
                        v_t.astype(v_pages.dtype))
                    attn = paged_attention(
                        q.astype(jnp.float32), k_pages[li], v_pages[li],
                        page_tbl, attend, impl=impl, interpret=interp,
                    )
                out = attn.reshape(n_slots, h_ * dh).astype(dt)
                x_t = x_t + out @ ap["Wo"].astype(dt)
                hh = _ln(lp["ln2"], x_t)
                hh = cfg_b.ffn_activation(
                    hh @ lp["W1"].astype(dt) + lp["b1"].astype(dt))
                x_t = x_t + (hh @ lp["W2"].astype(dt)
                             + lp["b2"].astype(dt))
            logits = _head_logits(head, params[head_name], x_t)
            keys = _slot_keys(seeds, gen_counts)
            nxt = jax.vmap(_sample_token)(
                logits.astype(jnp.float32), temps, top_ks, keys,
            )
            nxt = jnp.where(active, nxt, 0)
            return k_pages, v_pages, k_scales, v_scales, nxt

        return step

    def _make_verify(self):
        """The speculative verify-once program: ONE dispatch scores a
        C = spec_k + 1 token chunk per slot (the stream's last token
        plus its k draft proposals) through the SAME paged pool the
        plain step uses — shaped like a short prefill, compiled once,
        so speculation never grows the program set.

        Chunk row ``j`` of slot ``s`` writes K/V at sequence position
        ``seq_len + j`` and attends positions ``< seq_len + j + 1``
        (all C rows are written before the chunk attends; masking in
        `paged_attention_chunk` expresses the in-chunk causality), so
        its logits are bit-equal to what ``j`` sequential plain steps
        over the same tokens would produce.  Row ``j``'s token is
        sampled with the baseline key ``fold_in(key(seed),
        gen_count + j)`` — the exact `_slot_keys` schedule — which is
        what makes the harvested accept-prefix + corrected/bonus token
        BYTE-identical to plain decode at any temperature, not merely
        distribution-identical."""
        embed, pos, blocks, head = self._stack
        pos_name, head_name = self._pos_name, self._head_name
        block_names, embed_name = self._block_names, self._embed_name
        d, ps = self._d, self.kv.page_size
        h_, dh = self._n_heads, self._head_dim
        quant = self.kv.kv_dtype == "int8"
        impl = self.config.attention_impl
        interp = self.config.attention_interpret
        n_slots = self.config.slots
        mp = self.config.max_pages_per_seq
        c = self.spec_k + 1
        cap = mp * ps

        @jax.jit
        def verify(params, k_pages, v_pages, k_scales, v_scales,
                   page_tbl, seq_lens, chunk_toks, seeds, gen_counts,
                   temps, top_ks):
            dt = jnp.bfloat16 if self.model._bf16 else jnp.float32
            n = n_slots * c
            active = seq_lens > 0
            act_r = jnp.repeat(active, c)
            # flattened (S*C, ...) throughout so every matmul keeps the
            # plain step's 2-D shape (only M grows, S -> S*C)
            pos2 = seq_lens[:, None] + jnp.arange(c)[None, :]
            pos_idx = pos2.reshape(n)
            E = params[embed_name]["W"].astype(dt)
            x_t = embed._act()(E[chunk_toks.reshape(n)])
            pe = jax.vmap(
                lambda t: _pe_row(pos, params.get(pos_name, {}), t, d)
            )(pos_idx)
            x_t = x_t + pe.astype(dt)
            # write guard: a row past the table capacity lands on the
            # scratch page — NEVER index-clamp into a real page, that
            # would clobber a live row; rows within capacity but past
            # the allocated table hit entries that are already
            # SCRATCH_PAGE.  Accepted rows always fit (emit <= the
            # admission-funded budget), so only rejected-tail garbage
            # ever spills.
            tbl_rep = jnp.repeat(page_tbl, c, axis=0)
            write_ok = pos_idx < cap
            page_of = jnp.where(
                write_ok,
                tbl_rep[jnp.arange(n),
                        jnp.minimum(pos_idx // ps, mp - 1)],
                SCRATCH_PAGE,
            )
            row_of = jnp.where(write_ok, pos_idx % ps, 0)
            attend = jnp.where(active[:, None],
                               jnp.minimum(pos2 + 1, cap), 0)
            for li, (cfg_b, nm) in enumerate(zip(blocks, block_names)):
                lp = params[nm]
                ap = lp["attn"]
                hh = _ln(lp["ln1"], x_t)
                q = (hh @ ap["Wq"].astype(dt)).reshape(n, h_, dh)
                k_t = (hh @ ap["Wk"].astype(dt)).reshape(n, h_, dh)
                v_t = (hh @ ap["Wv"].astype(dt)).reshape(n, h_, dh)
                qc = q.astype(jnp.float32).reshape(n_slots, c, h_, dh)
                if quant:
                    kq, ksc = quantize_page_rows(k_t)
                    vq, vsc = quantize_page_rows(v_t)
                    k_pages = k_pages.at[li, page_of, row_of].set(kq)
                    v_pages = v_pages.at[li, page_of, row_of].set(vq)
                    k_scales = k_scales.at[li, page_of, row_of].set(ksc)
                    v_scales = v_scales.at[li, page_of, row_of].set(vsc)
                    attn = paged_attention_chunk(
                        qc, k_pages[li], v_pages[li], page_tbl, attend,
                        k_scale=k_scales[li], v_scale=v_scales[li],
                        impl=impl, interpret=interp,
                    )
                else:
                    k_pages = k_pages.at[li, page_of, row_of].set(
                        k_t.astype(k_pages.dtype))
                    v_pages = v_pages.at[li, page_of, row_of].set(
                        v_t.astype(v_pages.dtype))
                    attn = paged_attention_chunk(
                        qc, k_pages[li], v_pages[li], page_tbl, attend,
                        impl=impl, interpret=interp,
                    )
                out = attn.reshape(n, h_ * dh).astype(dt)
                x_t = x_t + out @ ap["Wo"].astype(dt)
                hh = _ln(lp["ln2"], x_t)
                hh = cfg_b.ffn_activation(
                    hh @ lp["W1"].astype(dt) + lp["b1"].astype(dt))
                x_t = x_t + (hh @ lp["W2"].astype(dt)
                             + lp["b2"].astype(dt))
            logits = _head_logits(head, params[head_name], x_t)
            keys = _slot_keys(
                jnp.repeat(seeds, c),
                (gen_counts[:, None] + jnp.arange(c)[None, :]).reshape(n),
            )
            nxt = jax.vmap(_sample_token)(
                logits.astype(jnp.float32), jnp.repeat(temps, c),
                jnp.repeat(top_ks, c), keys,
            )
            nxt = jnp.where(act_r, nxt, 0)
            return (k_pages, v_pages, k_scales, v_scales,
                    nxt.reshape(n_slots, c))

        return verify

    # -- the decode loop ---------------------------------------------------
    def _loop(self, my_gen: int) -> None:
        try:
            while not self._stop.is_set():
                with self._mu:
                    if self._loop_gen != my_gen:
                        return
                    n_active = sum(
                        r is not None for r in self._slot_req)
                self._refill(my_gen, block=(n_active == 0))
                with self._mu:
                    if self._loop_gen != my_gen:
                        return
                    n_active = sum(
                        r is not None for r in self._slot_req)
                if n_active == 0:
                    continue
                self._decode_step(my_gen)
        except Exception as exc:                      # never die silently
            log.exception("generation loop died")
            with self._mu:
                if self._loop_gen == my_gen:
                    self._fail_active_locked(
                        ServingError(f"generation loop died: {exc}"))

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _refill(self, my_gen: int, block: bool) -> None:
        """Admit queued streams into free slots — the continuous-batching
        join point, strictly BETWEEN decode steps."""
        free = self._free_slots()
        if not free:
            return
        if self.queue.depth == 0 and not block:
            return
        batch = self.queue.take_batch(
            len(free), linger_s=0.0, stop=self._stop,
            poll_s=self.config.poll_s,
        )
        t_taken = time.perf_counter()
        for req in batch:
            q0 = req.t_offer if req.t_offer is not None else req.t_submit
            wait = max(0.0, t_taken - q0)
            first_take = "queue" not in req.lat
            req.lat["queue"] = wait
            if first_take:
                # cancelled streams keep the segment too: a client
                # disconnect mid-queue still yields a complete chain
                self._trace_segment(req, "generation.admit", q0, wait)
            if req.cancelled:
                self._finish(req, "cancelled",
                             ServingRejected("shutdown", "cancelled"))
                continue
            slot = self._free_slots()
            if not slot:                  # more takes than slots freed
                self._offer_back(req)
                continue
            self._admit_to_slot(my_gen, slot[0], req)

    def _offer_back(self, req: GenerationRequest) -> None:
        if not self.queue.offer(req):
            self._finish(req, "queue_full",
                         ServingRejected("queue_full", "requeue failed"))

    def _admit_to_slot(self, my_gen: int, slot: int,
                       req: GenerationRequest) -> None:
        t_p = req.prompt.shape[0]
        if req.prefilled is None:
            t_b = bucket_length(t_p, self._quantum)
        else:
            t_b = int(req.prefilled["k"].shape[1])
        span = max(t_b, t_p + req.max_new)
        try:
            self.kv.alloc(req.rid, self.kv.pages_for(span))
        except KVPoolExhausted as exc:
            # the explicit 429 — the stream never stalls waiting on HBM
            self._finish(req, "kv_exhausted",
                         ServingRejected("kv_exhausted", str(exc)))
            try:
                self.flight.note_kv_exhausted()
            except Exception as e:
                log.debug("kv spike note failed: %s", e)
            return
        req.pages = self.kv.pages_for(span)
        if self._req_spec_k(req) > 0:
            # best-effort overhang so draft rows land in real pages;
            # a short pool (or a full page table) just means drafts
            # spill to scratch-masked rows (correct, slightly
            # wasteful) — never a 429
            table_cap = self.config.max_pages_per_seq * self.kv.page_size
            self.kv.reserve_speculative(
                req.rid, min(span + self.spec_k, table_cap))
        try:
            if req.prefilled is None:
                faults.maybe_fail("serving.prefill")
                t_pre0 = time.perf_counter()
                k, v, first, _ = self._run_prefill(req)
                t_pre1 = time.perf_counter()
                req.lat["prefill"] = t_pre1 - t_pre0
                self._trace_segment(req, "generation.prefill",
                                    t_pre0, t_pre1 - t_pre0, bucket=t_b)
                hand_t0 = None
            else:
                k, v = req.prefilled["k"], req.prefilled["v"]
                first = req.prefilled["first_token"]
                hand_t0 = req.prefilled.get("t_done_pc")
            t_w0 = time.perf_counter()
            tbl = self.kv.write_prefill(req.rid, k, v)
            t_w1 = time.perf_counter()
            # cross-replica handoff spans from the PREFILL replica's
            # completion mark (perf_counter is comparable in-process);
            # the lat entry excludes the decode-side queue wait the
            # "queue" segment already owns
            transfer = (max(0.0, req.t_offer - hand_t0)
                        if hand_t0 is not None and req.t_offer is not None
                        else 0.0)
            req.lat["handoff"] = transfer + (t_w1 - t_w0)
            span_t0 = hand_t0 if hand_t0 is not None else t_w0
            self._trace_segment(req, "generation.kv_handoff", span_t0,
                                max(0.0, t_w1 - span_t0), pages=len(tbl))
        except Exception as exc:
            self.kv.release(req.rid)
            self._finish(req, "error",
                         ServingError(f"prefill failed: {exc}"))
            return
        req._record(first)
        self._observe_ttft(req)
        self._count_tokens(1)
        if req.max_new <= 1 or first in req.stop_tokens:
            self.kv.release(req.rid)
            self._finish(req, "ok")
            return
        with self._mu:
            if self._loop_gen != my_gen:
                self.kv.release(req.rid)
                self._finish(
                    req, "error",
                    ServingError("engine respawned during admit"))
                return
            row = np.full(self.config.max_pages_per_seq, SCRATCH_PAGE,
                          np.int32)
            row[: len(tbl)] = tbl
            self._page_tbl[slot] = row
            self._seq_lens[slot] = t_p
            self._last_tok[slot] = first
            self._gen_counts[slot] = 1
            self._temps[slot] = req.temperature
            self._top_ks[slot] = req.top_k
            self._seeds[slot] = np.uint32(req.seed)
            self._slot_req[slot] = req
            req.t_slot = time.perf_counter()
        self._gauge_occupancy()

    def _decode_step(self, my_gen: int) -> None:
        """One token for every live slot: fault consult -> params
        snapshot (hot-swap boundary) -> watchdog-armed dispatch ->
        harvest (stop conditions, page release, slot free)."""
        try:
            faults.maybe_fail("serving.decode")
        except Exception as exc:
            self._step_failed(my_gen, exc)
            return
        if self.drafter is not None:
            drafts = self._gather_drafts(my_gen)
            if drafts is not None:
                self._verify_step(my_gen, drafts)
                return
            # nothing drafted (cold streams, rejection streak, per-
            # request opt-outs, fault fallback): ride the plain
            # one-token program — both programs are warm, so the mix
            # never compiles
            with self._stats_lock:
                self._spec_counts["plain_dispatches"] += 1
        if self._step_fn is None:
            self._step_fn = self._make_step()
        with self._mu:
            if self._loop_gen != my_gen:
                return
            args = (self._page_tbl.copy(), self._seq_lens.copy(),
                    self._last_tok.copy(), self._seeds.copy(),
                    self._gen_counts.copy(), self._temps.copy(),
                    self._top_ks.copy())
        with self._weights_lock:
            # the hot-swap boundary: push_weights installs under this
            # lock, so a swap lands BETWEEN decode steps and in-flight
            # streams continue (on the new weights) with zero drops
            params = self.model.params
        self._steps += 1
        self.watchdog.arm(self._steps)
        t0 = time.perf_counter()
        try:
            out = self._step_fn(
                params, self.kv.k_pages, self.kv.v_pages,
                self.kv.k_scales, self.kv.v_scales, *args,
            )
            nxt = np.asarray(out[4])
        except Exception as exc:
            self.watchdog.disarm(None)
            self._step_failed(my_gen, exc)
            return
        step_s = time.perf_counter() - t0
        self.watchdog.disarm(step_s)
        t_h0 = time.perf_counter()
        with self._mu:
            if self._loop_gen != my_gen:
                return                     # wedged + respawned: stale
            self.kv.k_pages, self.kv.v_pages = out[0], out[1]
            self.kv.k_scales, self.kv.v_scales = out[2], out[3]
            finished: list[tuple[GenerationRequest, bool]] = []
            stepped: list[tuple[GenerationRequest, int]] = []
            n_live = 0
            for s, req in enumerate(self._slot_req):
                if req is None:
                    continue
                if req.cancelled:
                    self._clear_slot(s)
                    finished.append((req, False))
                    continue
                n_live += 1
                tok = int(nxt[s])
                req._record(tok)
                self._seq_lens[s] += 1
                self._gen_counts[s] += 1
                self._last_tok[s] = tok
                stepped.append((req, int(self._gen_counts[s])))
                if (self._gen_counts[s] >= req.max_new
                        or tok in req.stop_tokens):
                    self._clear_slot(s)
                    finished.append((req, True))
            if stepped and self._rec.enabled:
                # batch-composition attribution: every co-resident
                # stream gets this step's span, tagged with who shared
                # the dispatch and how far along each stream is
                rids = [r.rid for r, _ in stepped]
                counts = {r.rid: c for r, c in stepped}
                for req, _ in stepped:
                    self._trace_segment(
                        req, "generation.decode_step", t0, step_s,
                        step=self._steps, batch=rids,
                        batch_tokens=counts,
                    )
        samp_s = max(0.0, time.perf_counter() - t_h0)
        for req, _ in stepped:
            # each co-resident stream is charged the full step wall
            # (like the shared dispatch segment of /v1/infer) plus the
            # host-side harvest/sampling bookkeeping
            req.lat["decode_compute"] = (
                req.lat.get("decode_compute", 0.0) + step_s)
            req.lat["sampling"] = req.lat.get("sampling", 0.0) + samp_s
        if self.breaker is not None:
            self.breaker.record_success()
        self._count_tokens(n_live)
        for req, ok in finished:
            self.kv.release(req.rid)
            if ok:
                self._finish(req, "ok")
            else:
                self._finish(req, "cancelled",
                             ServingRejected("shutdown", "cancelled"))
        self._gauge_occupancy()

    # -- speculative decode ------------------------------------------------
    def _req_spec_k(self, req: GenerationRequest) -> int:
        """Effective draft length for one stream: the engine's k,
        optionally lowered per request, zeroed by the fault-fallback
        latch.  Never above the engine k — the verify program's chunk
        width is static."""
        if self.drafter is None or req.spec_disabled:
            return 0
        k = (self.spec_k if req.spec_k is None
             else min(req.spec_k, self.spec_k))
        return max(0, k)

    def _gather_drafts(self, my_gen: int) -> Optional[list]:
        """Collect draft proposals for every live slot (engine thread,
        between dispatches).  Returns a per-slot list of int32 arrays,
        or None when no stream drafted — the caller falls back to the
        plain one-token program.  The ``serving.draft`` fault site is
        consulted once per drafting stream: ``raise`` latches the
        stream's drafter OFF for the rest of its life (plain decode,
        overhang pages truncated back); ``corrupt`` swaps the proposal
        for deterministic garbage the verify pass must reject with
        output unchanged."""
        with self._mu:
            if self._loop_gen != my_gen:
                return None
            live = list(enumerate(self._slot_req))
            gens = self._gen_counts.copy()
        drafts: list = [None] * self.config.slots
        any_draft = False
        for s, req in live:
            if req is None or req.cancelled:
                continue
            # drafting past the remaining budget is pure waste: the
            # harvest caps emitted tokens at max_new anyway
            k = min(self._req_spec_k(req),
                    req.max_new - int(gens[s]) - 1)
            if k <= 0:
                continue
            try:
                action = faults.maybe_fail("serving.draft")
            except Exception as exc:
                log.warning("drafter disabled for %s: %s", req.rid, exc)
                self._disable_spec(s, req)
                continue
            hist = np.concatenate(
                [req.prompt, np.asarray(req.tokens_so_far(), np.int32)])
            if action == "corrupt":
                # deterministic garbage, independent of the real
                # drafter: rejection sampling must shrug it off
                d = (int(hist[-1]) + 1
                     + np.arange(k, dtype=np.int32) * 17) % self._vocab
                d = d.astype(np.int32)
            else:
                try:
                    d = np.asarray(self.drafter.draft(hist, k),
                                   np.int32).reshape(-1)[:k]
                except Exception as exc:
                    log.warning("drafter failed for %s: %s",
                                req.rid, exc)
                    self._disable_spec(s, req)
                    continue
            if d.size:
                drafts[s] = d
                any_draft = True
        return drafts if any_draft else None

    def _disable_spec(self, s: int, req: GenerationRequest) -> None:
        """Latch one stream to plain decode (the mid-stream fallback)
        and give back its speculative overhang pages — the
        truncate-on-reject rollback, so a disabled drafter can't leak
        reserved capacity for the stream's remaining life."""
        req.spec_disabled = True
        with self._stats_lock:
            self._spec_counts["fallbacks"] += 1
        freed = self.kv.truncate_to(req.rid,
                                    req.pages * self.kv.page_size)
        if freed:
            with self._mu:
                if self._slot_req[s] is req:
                    self._page_tbl[s, req.pages:] = SCRATCH_PAGE

    def _verify_step(self, my_gen: int, drafts: list) -> None:
        """One verify-once dispatch: score the (spec_k + 1)-token chunk
        for every live slot, then emit each stream's accepted draft
        prefix plus the corrected/bonus sample — 1..k+1 tokens per
        stream, byte-identical to sequential plain decode.  Mirrors
        `_decode_step`'s structure (fault consult already happened);
        the watchdog arms with the chunk width so the EWMA deadline
        stays per-token-normalized."""
        if self._verify_fn is None:
            self._verify_fn = self._make_verify()
        c = self.spec_k + 1
        n_slots = self.config.slots
        with self._mu:
            if self._loop_gen != my_gen:
                return
            chunk = np.zeros((n_slots, c), np.int32)
            chunk[:, 0] = self._last_tok
            dl = np.zeros(n_slots, np.int32)
            for s in range(n_slots):
                d = drafts[s]
                if d is None or d.size == 0:
                    continue
                m = min(int(d.size), self.spec_k)
                chunk[s, 1:1 + m] = d[:m]
                dl[s] = m
            gen0 = self._gen_counts.copy()
            args = (self._page_tbl.copy(), self._seq_lens.copy(),
                    chunk, self._seeds.copy(), gen0,
                    self._temps.copy(), self._top_ks.copy())
        with self._weights_lock:
            params = self.model.params
        self._steps += 1
        self.watchdog.arm(self._steps, n_steps=c)
        t0 = time.perf_counter()
        try:
            out = self._verify_fn(
                params, self.kv.k_pages, self.kv.v_pages,
                self.kv.k_scales, self.kv.v_scales, *args,
            )
            tgt = np.asarray(out[4])
        except Exception as exc:
            self.watchdog.disarm(None)
            self._step_failed(my_gen, exc)
            return
        step_s = time.perf_counter() - t0
        self.watchdog.disarm(step_s)
        t_h0 = time.perf_counter()
        sp = {"drafted": 0, "accepted": 0, "rejected": 0, "bonus": 0}
        emitted_total = 0
        with self._mu:
            if self._loop_gen != my_gen:
                return                     # wedged + respawned: stale
            self.kv.k_pages, self.kv.v_pages = out[0], out[1]
            self.kv.k_scales, self.kv.v_scales = out[2], out[3]
            finished: list[tuple[GenerationRequest, bool]] = []
            stepped: list[tuple[GenerationRequest, int, int]] = []
            for s, req in enumerate(self._slot_req):
                if req is None:
                    continue
                if req.cancelled:
                    self._clear_slot(s)
                    finished.append((req, False))
                    continue
                budget = req.max_new - int(gen0[s])
                d_len = int(dl[s])
                row = tgt[s]
                # accept-prefix: row j's target sample IS what plain
                # decode would emit at that position, so a match means
                # the draft token was exactly right; the first
                # mismatch's sample is the corrected token, an all-
                # match chunk appends the bonus sample at row k
                a = 0
                while a < d_len and int(row[a]) == int(chunk[s, a + 1]):
                    a += 1
                emit = min(a + 1, budget)
                toks = [int(row[j]) for j in range(emit)]
                fin = False
                for j, t in enumerate(toks):
                    if t in req.stop_tokens:
                        emit = j + 1
                        toks = toks[:emit]
                        fin = True
                        break
                for t in toks:
                    req._record(t)
                self._seq_lens[s] += emit
                self._gen_counts[s] += emit
                self._last_tok[s] = toks[-1]
                accepted = min(emit, a)
                sp["drafted"] += d_len
                sp["accepted"] += accepted
                sp["rejected"] += d_len - accepted
                sp["bonus"] += emit - accepted
                req.spec_drafted += d_len
                req.spec_accepted += accepted
                emitted_total += emit
                stepped.append((req, int(self._gen_counts[s]), emit))
                if self._gen_counts[s] >= req.max_new or fin:
                    self._clear_slot(s)
                    finished.append((req, True))
            if stepped and self._rec.enabled:
                rids = [r.rid for r, _, _ in stepped]
                counts = {r.rid: n for r, n, _ in stepped}
                emits = {r.rid: e for r, _, e in stepped}
                for req, _, _ in stepped:
                    self._trace_segment(
                        req, "generation.decode_step", t0, step_s,
                        step=self._steps, batch=rids,
                        batch_tokens=counts, emitted=emits,
                        speculative=True,
                    )
        samp_s = max(0.0, time.perf_counter() - t_h0)
        for req, _, _ in stepped:
            # same attribution semantics as the plain step: every co-
            # resident stream is charged the full dispatch wall (the
            # per-token view divides by tokens_generated in stats())
            req.lat["decode_compute"] = (
                req.lat.get("decode_compute", 0.0) + step_s)
            req.lat["sampling"] = req.lat.get("sampling", 0.0) + samp_s
        if self.breaker is not None:
            self.breaker.record_success()
        self._count_tokens(emitted_total)
        self._count_spec(sp, emitted_total)
        for req, ok in finished:
            self.kv.release(req.rid)
            if ok:
                self._finish(req, "ok")
            else:
                self._finish(req, "cancelled",
                             ServingRejected("shutdown", "cancelled"))
        self._gauge_occupancy()

    def _count_spec(self, sp: dict, emitted: int) -> None:
        """One verify dispatch's speculative accounting: host counters
        for stats() plus the pre-declared spec metric families."""
        with self._stats_lock:
            for kind, v in sp.items():
                self._spec_counts[kind] += v
            self._spec_counts["emitted"] += emitted
            self._spec_counts["verify_dispatches"] += 1
            drafted = self._spec_counts["drafted"]
            ratio = (self._spec_counts["accepted"] / drafted
                     if drafted else 0.0)
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            reg = registry()
            ctr = reg.counter("dl4jtpu_spec_tokens_total")
            for kind, v in sp.items():
                if v:
                    ctr.inc(v, kind=kind)
            reg.gauge("dl4jtpu_spec_acceptance_ratio").set(
                round(ratio, 4))
            reg.histogram("dl4jtpu_spec_tokens_per_dispatch").observe(
                emitted)
        except Exception as e:
            log.debug("spec metric failed: %s", e)

    def _clear_slot(self, s: int) -> None:
        """Caller holds self._mu.  Pages are released by the caller
        (outside the lock) via kv.release."""
        self._slot_req[s] = None
        self._page_tbl[s, :] = SCRATCH_PAGE
        self._seq_lens[s] = 0
        self._last_tok[s] = 0
        self._gen_counts[s] = 0
        self._temps[s] = 0.0
        self._top_ks[s] = 0
        self._seeds[s] = 0

    # -- failure paths -----------------------------------------------------
    def _step_failed(self, my_gen: int, exc: BaseException) -> None:
        log.error("generation decode step failed: %s", exc)
        tripped = False
        if self.breaker is not None:
            was = self.breaker.state
            self.breaker.record_failure()
            tripped = was != "open" and self.breaker.state == "open"
        with self._mu:
            if self._loop_gen != my_gen:
                return
            self._fail_active_locked(
                ServingError(f"decode step failed: {exc}"))
        self._gauge_occupancy()
        if tripped:
            try:
                self.flight.dump("breaker_open",
                                 context={"error": str(exc)})
            except Exception as e:
                log.debug("breaker flight dump failed: %s", e)

    def _fail_active_locked(self, exc: BaseException,
                            outcome: str = "error") -> None:
        """Caller holds self._mu: fail every in-flight stream and
        release ALL of their pages — the watchdog-abort contract.
        Every stream settles through `_finish`, so aborted streams get
        closed chains, outcome counts and flight records too."""
        for s, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._clear_slot(s)
            self.kv.release(req.rid)
            self._finish(req, outcome, exc)

    def _on_wedged(self, event: dict) -> None:
        """Watchdog stage-3 abort: the dispatched step never returned.
        Fail every in-flight stream, release all their pages, trip the
        breaker, and respawn the loop under a new generation — the
        wedged thread's eventual return sees a stale generation and
        discards itself."""
        log.error("generation decode step wedged: %s", event)
        if self.breaker is not None:
            self.breaker.record_failure()
        with self._mu:
            self._loop_gen += 1
            gen = self._loop_gen
            self._fail_active_locked(
                ServingError(f"decode step wedged: {event.get('stage')}"),
                outcome="wedged",
            )
        self._gauge_occupancy()
        try:
            self.flight.dump("watchdog_abort", context=dict(event))
        except Exception as e:
            log.debug("watchdog flight dump failed: %s", e)
        if not self._stop.is_set():
            self._thread = threading.Thread(
                target=self._loop, args=(gen,),
                name="dl4jtpu-generation", daemon=True,
            )
            self._thread.start()

    # -- the fate point ----------------------------------------------------
    def _finish(self, req: GenerationRequest, outcome: str,
                exc: Optional[BaseException] = None) -> None:
        """Settle one stream EXACTLY ONCE: finalize the latency
        breakdown, record the ``generation.stream`` root span, bump the
        per-outcome counter, offer the stream to the slow ring, append
        the flight record, then release the client (`_fail`/`_complete`).
        Racing settlers (watchdog abort vs stop) claim via `trace_done`
        under the request lock; losers are silent no-ops."""
        with req._lock:
            if req.trace_done:
                return
            req.trace_done = True
            req.outcome = outcome
        t_fate = time.perf_counter()
        latency = max(0.0, t_fate - req.t_submit)
        if req.t_slot is not None:
            resid = (t_fate - req.t_slot
                     - req.lat.get("decode_compute", 0.0)
                     - req.lat.get("sampling", 0.0))
            req.lat["decode_queue"] = max(0.0, resid)
        self._observe_breakdown(req.lat)
        self._count_stream(outcome)
        if req.trace_id is not None and self._rec.enabled:
            args = dict(otrace.trace_args(req.trace_id, req.root_span,
                                          req.root_parent))
            if exc is not None:
                args["error"] = str(exc)
            self._rec.add_complete(
                "generation.stream", req.t_submit, latency,
                cat="generation", outcome=outcome, rid=req.rid,
                tokens=len(req.tokens), **args,
            )
        self._note_slow(req, outcome, latency)
        self._flight_record(req, outcome, latency, exc)
        if exc is not None:
            req._fail(exc)
        else:
            req._complete()

    def _note_slow(self, req: GenerationRequest, outcome: str,
                   latency_s: float) -> None:
        """Offer one settled stream to the slowest-streams exemplar
        ring (bounded, latency-descending — the generation twin of
        server._note_slow)."""
        entry = {
            "kind": "generate",
            "rid": req.rid,
            "trace": (f"{req.trace_id:x}" if req.trace_id is not None
                      else None),
            "trace_id": req.trace_id,
            "outcome": outcome,
            "latency_s": round(latency_s, 6),
            "ttft_s": (round(req.ttft_s, 6) if req.ttft_s is not None
                       else None),
            "tokens": len(req.tokens),
            "t_wall": time.time(),
            "breakdown_s": {k: round(v, 6) for k, v in req.lat.items()},
        }
        with self._stats_lock:
            slow = self._slow
            if len(slow) >= GEN_SLOW_RING_CAP and \
                    latency_s <= slow[-1]["latency_s"]:
                return
            slow.append(entry)
            slow.sort(key=lambda e: -e["latency_s"])
            del slow[GEN_SLOW_RING_CAP:]

    def slow_streams(self, spans: bool = True) -> list[dict]:
        """The slowest-stream exemplars (latency-descending), each with
        its breakdown and — when tracing is on — its full causal span
        chain.  Served at ``GET /api/generation/slow`` and merged into
        ``GET /api/serving/slow``."""
        with self._stats_lock:
            out = [dict(e) for e in self._slow]
        if spans and self._rec.enabled:
            for e in out:
                if e["trace_id"] is not None:
                    e["spans"] = self._rec.trace_chain(e["trace_id"])
        for e in out:
            e.pop("trace_id", None)
        return out

    def _flight_record(self, req: GenerationRequest, outcome: str,
                       latency_s: float,
                       exc: Optional[BaseException]) -> None:
        try:
            self.flight.record({
                "rid": req.rid,
                "trace": (f"{req.trace_id:x}"
                          if req.trace_id is not None else None),
                "outcome": outcome,
                "error": str(exc) if exc is not None else None,
                "prompt_len": int(req.prompt.shape[0]),
                "max_new": req.max_new,
                "tokens": len(req.tokens),
                "ttft_s": req.ttft_s,
                "latency_s": round(latency_s, 6),
                "pages_held": req.pages,
                "breakdown_s": {k: round(v, 6)
                                for k, v in req.lat.items()},
                "t_wall": time.time(),
            })
        except Exception as e:
            log.debug("flight record failed: %s", e)

    def _flight_context(self) -> dict:
        """Engine/KV snapshot merged into every flight dump."""
        return {"stats": self.stats()}

    # -- introspection -----------------------------------------------------
    def active_streams(self) -> int:
        with self._mu:
            return sum(r is not None for r in self._slot_req)

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until no stream is in flight and the queue is empty —
        True when drained within the timeout."""
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            if self.active_streams() == 0 and self.queue.depth == 0:
                return True
            time.sleep(self.config.poll_s)
        return False

    def stats(self) -> dict:
        with self._mu:
            active = sum(r is not None for r in self._slot_req)
        with self._stats_lock:
            totals = dict(self._lat_totals)
            outcomes = dict(self._stream_outcomes)
            settled = self._streams_settled
            slow_n = len(self._slow)
            spec = dict(self._spec_counts)
        total_s = sum(totals.values())
        # per-token normalization: a speculative step emits 1..k+1
        # tokens per dispatch, so cross-config comparisons read the
        # seconds_per_token view, not raw segment walls
        n_tok = max(1, self._tokens_out)
        breakdown = {
            k: {
                "seconds_total": round(v, 6),
                "fraction": (round(v / total_s, 4)
                             if total_s > 0 else 0.0),
                "seconds_per_token": round(v / n_tok, 9),
            }
            for k, v in totals.items()
        }
        drafted = spec["drafted"]
        out = {
            "slots": self.config.slots,
            "active_streams": active,
            "queue_depth": self.queue.depth,
            "decode_steps": self._steps,
            "tokens_generated": self._tokens_out,
            "tokens_per_s": round(self.tokens_per_s(), 4),
            "streams": {"settled": settled, "outcomes": outcomes},
            "latency_breakdown": breakdown,
            "slow_streams": slow_n,
            "flight": {"records": len(self.flight),
                       "dumps": self.flight.dumps_written},
            "kv": self.kv.stats(),
            "speculative": {
                "enabled": self.spec_k > 0,
                "k": self.spec_k,
                "drafter": (self.drafter.name
                            if self.drafter is not None else None),
                "drafted": drafted,
                "accepted": spec["accepted"],
                "rejected": spec["rejected"],
                "bonus": spec["bonus"],
                "acceptance_ratio": (
                    round(spec["accepted"] / drafted, 4)
                    if drafted else 0.0),
                "verify_dispatches": spec["verify_dispatches"],
                "plain_dispatches": spec["plain_dispatches"],
                "tokens_per_dispatch": (
                    round(spec["emitted"] / spec["verify_dispatches"], 4)
                    if spec["verify_dispatches"] else 0.0),
                "fallbacks": spec["fallbacks"],
            },
        }
        return out

    def health_summary(self) -> dict:
        """Compact generation block for `InferenceServer.health()` —
        the Router (and the fleet push behind it) sees a replica's
        decode pressure and stream outcomes without a /metrics
        scrape."""
        with self._mu:
            active = sum(r is not None for r in self._slot_req)
        with self._stats_lock:
            outcomes = dict(self._stream_outcomes)
            drafted = self._spec_counts["drafted"]
            accepted = self._spec_counts["accepted"]
        out = {
            "active_streams": active,
            "queue_depth": self.queue.depth,
            "kv_occupancy": round(self.kv.occupancy(), 4),
            "tokens_per_s": round(self.tokens_per_s(), 4),
            "stream_outcomes": outcomes,
            "flight_dumps": self.flight.dumps_written,
        }
        if self.spec_k > 0:
            out["spec_acceptance_ratio"] = (
                round(accepted / drafted, 4) if drafted else 0.0)
        return out

    def tokens_per_s(self) -> float:
        """Recent aggregate decode rate over the trailing rate-sample
        window (0.0 until two samples exist)."""
        with self._stats_lock:
            if len(self._rate_samples) < 2:
                return 0.0
            t0, n0 = self._rate_samples[0]
            t1, n1 = self._rate_samples[-1]
        dt = t1 - t0
        return (n1 - n0) / dt if dt > 0 else 0.0

    # -- telemetry ---------------------------------------------------------
    def _count_tokens(self, n: int) -> None:
        if n <= 0:
            return
        self._tokens_out += n
        now = time.perf_counter()
        with self._stats_lock:
            self._rate_samples.append((now, self._tokens_out))
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            reg = registry()
            reg.counter("dl4jtpu_decode_tokens_total").inc(n)
            reg.gauge("dl4jtpu_generation_tokens_per_s").set(
                round(self.tokens_per_s(), 4))
        except Exception as e:
            log.debug("decode token metric failed: %s", e)

    def _count_stream(self, outcome: str) -> None:
        """One settled (or synchronously rejected) stream, by outcome —
        the availability numerator/denominator of stream-success SLOs."""
        with self._stats_lock:
            self._streams_settled += 1
            self._stream_outcomes[outcome] = (
                self._stream_outcomes.get(outcome, 0) + 1)
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().counter("dl4jtpu_generation_streams_total").inc(
                outcome=outcome)
        except Exception as e:
            log.debug("stream outcome metric failed: %s", e)

    def _count_admitted(self) -> None:
        """Demand counter behind throughput SLOs: admitted streams keep
        a stalled window non-idle (see SLObjective kind="throughput")."""
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().counter(
                "dl4jtpu_generation_streams_admitted_total").inc()
        except Exception as e:
            log.debug("admitted stream metric failed: %s", e)

    def _observe_breakdown(self, lat: dict) -> None:
        try:
            fams = _gen_breakdown_families()
            with self._stats_lock:
                for seg in GEN_BREAKDOWN_SEGMENTS:
                    v = lat.get(seg)
                    if v is None:
                        continue
                    self._lat_totals[seg] += v
                    fams[seg].observe(v)
        except Exception as e:
            log.debug("generation breakdown observe failed: %s", e)

    def _observe_ttft(self, req: GenerationRequest) -> None:
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            if req.ttft_s is not None:
                registry().histogram("dl4jtpu_ttft_seconds").observe(
                    req.ttft_s)
        except Exception as e:
            log.debug("ttft metric failed: %s", e)

    def _gauge_occupancy(self) -> None:
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            with self._mu:
                active = sum(r is not None for r in self._slot_req)
            registry().gauge("dl4jtpu_decode_batch_occupancy").set(
                active / max(1, self.config.slots))
        except Exception as e:
            log.debug("occupancy gauge failed: %s", e)
