"""Front-door Router — health-aware load balancing over serving replicas.

One `InferenceServer` degrades instead of dying (PR 10); a fleet of
them needs a front door that keeps the CLIENT's view degradation-free
while individual replicas wedge, shed or recover.  The router's four
jobs, in the order a request meets them:

- **pull-based balancing**: every replica advertises shed pressure
  (`InferenceServer.health()`: queue-depth fraction, breaker state,
  batch-latency EWMA folded into one [0,1] number) and the router sends
  each request to the least-pressured live replica — it stops sending
  to a loaded replica *before* that replica starts answering 429/503,
  instead of after.
- **ejection + probation**: a replica that fails consecutively
  (`eject_threshold`), blows the per-try deadline (a wedged dispatch),
  or drops its connection is EJECTED into probation — the PR 10
  circuit breaker's OPEN/HALF_OPEN ladder at fleet scope.  After
  `probation_s` exactly one probe request is routed to it; success
  re-admits, failure restarts the timer.  Ejections are counted by
  reason (`dl4jtpu_replica_ejections_total`), never silent.
- **bounded retries**: inference is idempotent (a pure forward pass),
  so a failed or rejected try is retried on a DIFFERENT replica under
  an explicit per-request `retry_budget`.  Every retry is counted; on
  budget exhaustion the ORIGINAL error surfaces — the client learns
  what actually went wrong first, not what the last desperate try hit.
- **one optional hedge**: with `hedge_after_s` set, a try that has not
  completed by then gets ONE duplicate dispatch on another replica;
  the first result wins and the slower duplicate is discarded
  (cancelled, so the losing replica's ledger still balances).  Counted
  under `dl4jtpu_router_hedges_total`.

Fault site ``serving.route`` is consulted at submit entry: ``raise``
becomes an explicit ``route_fault`` rejection (the front door fails
closed), ``delay`` a slow front door.  Every routed try lands on the
telemetry spine as
``dl4jtpu_router_requests_total{replica,outcome}``, and a registry
collector refreshes ``dl4jtpu_router_replica_pressure{replica}`` at
scrape time so the fleet scrape carries per-replica headroom.

Request-level observability (ISSUE 13): with tracing enabled, a routed
request emits ONE causal chain rooted at ``router.request`` — each try
is a ``router.try`` span (args: replica, outcome), the hedge a
``router.hedge`` span, and every replica-side chain (admit -> queue
wait -> batch form -> dispatch) parents under the try that submitted
it, so Perfetto shows the request hopping replicas.  Always-on (no
tracing needed): ``dl4jtpu_router_overhead_seconds`` observes, per
successful request, client wall MINUS the winning try's service time —
the retry + hedge + pick tax the front door added.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import weakref
from typing import Optional

from deeplearning4j_tpu.observe import trace as otrace
from deeplearning4j_tpu.runtime import faults
from deeplearning4j_tpu.serving.admission import (
    ServingError, ServingRejected, ServingTimeout,
)

log = logging.getLogger("deeplearning4j_tpu")

ACTIVE = "active"
PROBATION = "probation"

#: rejection reasons that mean "this replica never ran the request" —
#: always safe to retry elsewhere (the failure classes that DO count
#: toward ejection are handled separately)
_RETRYABLE_REJECTS = frozenset((
    "queue_full", "deadline", "breaker_open", "admit_fault",
    "shutdown", "replica_dead",
))


@dataclasses.dataclass
class RouterConfig:
    """Front-door knobs (docs/serving.md has the full table)."""

    eject_threshold: int = 3       # consecutive try failures to eject
    probation_s: float = 1.0       # ejected -> single-probe window
    retry_budget: int = 1          # cross-replica retries per request
    hedge_after_s: Optional[float] = None   # None = hedging off
    pressure_ceiling: float = 0.9  # avoid replicas advertising >= this
    health_refresh_s: float = 0.05  # per-replica health pull cache
    default_deadline_s: float = 1.0
    try_timeout_s: Optional[float] = None  # per-try cap (wedge detector)


#: replica roles for prefill/decode disaggregation: ``prefill`` runs
#: only prompt prefill programs, ``decode`` only the continuous decode
#: batch, ``both`` serves everything (the default single-group fleet)
ROLES = ("prefill", "decode", "both")


class ReplicaHandle:
    """One routable replica: an in-process `InferenceServer` today (the
    HTTP frontend wraps the same object, so a remote handle only needs
    to speak `/healthz` + `/v1/infer` — same payloads, same contract).
    Caches the pulled health for `refresh_s` so a hot router does not
    hammer the replica's locks on every request.

    ``role`` assigns the replica to a generation serving group
    (prefill / decode / both); `Router.pick_for_role` steers token
    traffic by it, while classic `/v1/infer` routing stays
    role-agnostic."""

    def __init__(self, name: str, server, refresh_s: float = 0.05,
                 role: str = "both"):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.name = name
        self.server = server
        self.role = role
        self.refresh_s = float(refresh_s)
        self._lock = threading.Lock()
        self._cached: Optional[dict] = None
        self._cached_at = 0.0
        self._dead = False

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    def kill(self) -> None:
        """Mark the replica dead (the fleet's hard-kill: a real process
        would answer connection-refused).  Routed submits fail fast with
        an explicit ``replica_dead`` rejection."""
        with self._lock:
            self._dead = True
            self._cached = None

    def revive(self) -> None:
        with self._lock:
            self._dead = False
            self._cached = None

    def health(self) -> dict:
        with self._lock:
            if self._dead:
                return {"status": "dead", "shed_pressure": 1.0,
                        "breaker_state": "dead"}
            now = time.monotonic()
            if (self._cached is not None
                    and now - self._cached_at < self.refresh_s):
                return self._cached
        h = self.server.health()       # replica locks: outside ours
        with self._lock:
            if not self._dead:
                self._cached = h
                self._cached_at = time.monotonic()
        return h

    def pressure(self) -> float:
        return float(self.health().get("shed_pressure", 1.0))

    def submit(self, features, deadline_s: float, trace_ctx=None):
        if self.dead:
            raise ServingRejected("replica_dead", self.name)
        return self.server.submit(features, deadline_s=deadline_s,
                                  trace_ctx=trace_ctx)


class Router:
    """The fleet's front door.  Thread-safe: many client threads route
    concurrently while the health collector scrapes."""

    def __init__(self, replicas: list, config: Optional[RouterConfig] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.config = config or RouterConfig()
        # process-unique router name: two fleets in one process must
        # not merge their per-replica metric series (replica names are
        # only unique WITHIN a fleet)
        self.name = _next_router_name()
        self._lock = threading.Lock()
        # per-replica routing state: the fleet-scope breaker ladder
        self._state: dict[str, dict] = {
            h.name: {
                "state": ACTIVE, "fails": 0, "ejected_at": 0.0,
                "probe_inflight": False, "ejections": 0,
            }
            for h in self.replicas
        }
        if len(self._state) != len(self.replicas):
            raise ValueError("replica names must be unique")
        self._counts: dict[str, int] = {
            "requests": 0, "ok": 0, "failed": 0, "client_errors": 0,
            "retries": 0, "hedges": 0, "ejections": 0, "readmissions": 0,
        }
        self._rr = 0                    # tie-break rotation
        self._rec = otrace.tracer()     # cached: no lock per request
        _register_router(self)

    # -- routing state ------------------------------------------------------
    def replica_states(self) -> dict:
        with self._lock:
            return {
                name: {"state": st["state"], "fails": st["fails"],
                       "ejections": st["ejections"]}
                for name, st in self._state.items()
            }

    def stats(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
        return {
            "name": self.name,
            "replicas": self.replica_states(),
            "pressure": {h.name: round(h.pressure(), 6)
                         for h in self.replicas},
            **counts,
        }

    def _pick(self, exclude: frozenset = frozenset()):
        """Choose the next replica: an open probation probe wins (timed
        single-probe re-admission), else the least-pressured ACTIVE
        replica under the ceiling, else the least-pressured ACTIVE one
        at all.  Raises ``ServingRejected(no_replicas)`` when nothing
        is routable."""
        # pull health OUTSIDE the router lock (handles lock themselves)
        pressures = {
            h.name: h.pressure() for h in self.replicas
            if h.name not in exclude and not h.dead
        }
        dead = [h.name for h in self.replicas if h.dead]
        now = time.monotonic()
        newly_ejected = []
        if dead:
            # a dead handle (connection refused) is ejected the moment
            # the router notices — no try wasted on it, still counted
            with self._lock:
                for name in dead:
                    st = self._state[name]
                    if st["state"] == ACTIVE:
                        st["state"] = PROBATION
                        st["ejected_at"] = now
                        st["probe_inflight"] = False
                        st["ejections"] += 1
                        self._counts["ejections"] += 1
                        newly_ejected.append(name)
        for name in newly_ejected:
            log.warning("router ejected replica %s into probation (dead)",
                        name)
            _count_ejection("dead")
        with self._lock:
            probe = None
            candidates = []
            for h in self.replicas:
                if h.name not in pressures:
                    continue
                st = self._state[h.name]
                if st["state"] == PROBATION:
                    if (not st["probe_inflight"]
                            and now - st["ejected_at"]
                            >= self.config.probation_s):
                        probe = probe or h
                    continue
                candidates.append(h)
            if probe is not None:
                self._state[probe.name]["probe_inflight"] = True
                return probe, True
            if not candidates:
                raise ServingRejected(
                    "no_replicas",
                    f"no routable replica ({len(self.replicas)} total, "
                    f"{len(exclude)} excluded this request)",
                )
            under = [h for h in candidates
                     if pressures[h.name] < self.config.pressure_ceiling]
            pool = under or candidates
            best = min(pressures[h.name] for h in pool)
            ties = [h for h in pool if pressures[h.name] <= best + 1e-9]
            self._rr += 1
            return ties[self._rr % len(ties)], False

    def pick_for_role(self, need: str, trace_ctx=None):
        """Least-pressured live ACTIVE replica whose role serves
        ``need`` (``prefill`` or ``decode``; ``both`` replicas serve
        either).  Pressure includes the KV-occupancy term
        (`InferenceServer.shed_pressure`), so a decode replica whose
        page pool is filling sheds token traffic here — BEFORE its
        admissions start answering ``kv_exhausted`` 429s.  Raises
        ``ServingRejected(no_replicas)`` when the role group is empty
        or fully ejected.  `trace_ctx` is a generation stream's
        ``(trace_id, root_span)``: the pick records a ``router.pick``
        span into that chain, so the cluster timeline shows WHY a
        stream landed on its prefill/decode replicas."""
        if need not in ("prefill", "decode"):
            raise ValueError(f"need must be prefill|decode, got {need!r}")
        t0_pc = time.perf_counter()
        pressures = {
            h.name: h.pressure() for h in self.replicas
            if not h.dead and h.role in (need, "both")
        }
        with self._lock:
            candidates = [
                h for h in self.replicas
                if h.name in pressures
                and self._state[h.name]["state"] == ACTIVE
            ]
            if not candidates:
                raise ServingRejected(
                    "no_replicas",
                    f"no routable {need} replica "
                    f"({len(self.replicas)} total)",
                )
            under = [h for h in candidates
                     if pressures[h.name] < self.config.pressure_ceiling]
            pool = under or candidates
            best = min(pressures[h.name] for h in pool)
            ties = [h for h in pool if pressures[h.name] <= best + 1e-9]
            self._rr += 1
            chosen = ties[self._rr % len(ties)]
        self._trace_pick(need, chosen.name, trace_ctx, t0_pc)
        return chosen

    def _trace_pick(self, need: str, replica: str, trace_ctx,
                    t0_pc: float) -> None:
        """One ``router.pick`` span in a generation stream's chain
        (no-op without a context or with tracing off)."""
        try:
            rec = otrace.tracer()
            if trace_ctx is None or not rec.enabled:
                return
            trace_id, parent = trace_ctx
            rec.add_complete(
                "router.pick", t0_pc, time.perf_counter() - t0_pc,
                cat="generation",
                **otrace.trace_args(trace_id, otrace.next_id(), parent),
                role=need, replica=replica,
            )
        except Exception as e:
            log.debug("router pick span failed: %s", e)

    def _record(self, handle, outcome: str, probe: bool,
                eject_reason: Optional[str] = None) -> None:
        """Fold one try's outcome into the replica's routing state.
        ``outcome``: ok | error | timeout | client_timeout | rejected |
        dead.  Ejection:
        immediately for dead tries (connection refused is unambiguous),
        after `eject_threshold` consecutive errors/timeouts otherwise —
        the reason records ``wedged`` when the per-try deadline was the
        last straw (a single short-deadline client must not eject a
        healthy replica).  Sheds (``rejected``) are load signals, not
        failures — the pressure pull handles those."""
        ejected = readmitted = None
        with self._lock:
            st = self._state[handle.name]
            if probe:
                st["probe_inflight"] = False
            if outcome == "ok":
                st["fails"] = 0
                # ONLY the designated probe re-admits: a straggler ok
                # from a request dispatched before the ejection (e.g. a
                # dying replica draining its queue) must not flap the
                # replica back into rotation
                if st["state"] == PROBATION and probe:
                    st["state"] = ACTIVE
                    self._counts["readmissions"] += 1
                    readmitted = True
            elif outcome in ("error", "timeout", "dead"):
                st["fails"] += 1
                fails = st["fails"]
                reason = eject_reason or (
                    "wedged" if outcome == "timeout"
                    else "dead" if outcome == "dead"
                    else "consecutive_failures"
                )
                if st["state"] == PROBATION:
                    # failed probe: restart the timer
                    st["ejected_at"] = time.monotonic()
                elif (outcome == "dead"
                      or fails >= self.config.eject_threshold):
                    st["state"] = PROBATION
                    st["ejected_at"] = time.monotonic()
                    st["probe_inflight"] = False
                    st["ejections"] += 1
                    self._counts["ejections"] += 1
                    ejected = (reason, fails)
            elif outcome == "client_timeout":
                # the CLIENT's deadline expired mid-try with no per-try
                # cap binding: says nothing about the replica's health
                # (a short-deadline client must not eject a healthy
                # fleet) — counted in the metric, no failure streak
                pass
            # "rejected": neither a success streak nor a failure streak
        if ejected:
            log.warning("router ejected replica %s into probation (%s, "
                        "%d consecutive failure(s))",
                        handle.name, ejected[0], ejected[1])
            ejected = ejected[0]
            _count_ejection(ejected)
        if readmitted:
            log.info("router re-admitted replica %s (probe succeeded)",
                     handle.name)
        _count_try(
            self.name, handle.name,
            {"dead": "rejected", "client_timeout": "timeout"}.get(
                outcome, outcome,
            ),
        )

    # -- the request path ---------------------------------------------------
    def infer(self, features, deadline_s: Optional[float] = None):
        """Route one request: pick by pulled pressure, retry idempotent
        failures on a different replica under the retry budget, hedge
        the latency tail once when configured.  Raises the ORIGINAL
        error when the budget runs out — every retry and hedge is
        counted, never silent."""
        try:
            action = faults.maybe_fail("serving.route")
        except Exception as exc:
            # a front door that raises is a failing ROUTER, not a failing
            # request: explicit rejection, client may retry
            raise ServingRejected("route_fault", str(exc)) from exc
        if action is not None:
            raise ServingRejected("route_fault", f"injected {action}")
        deadline_s = (self.config.default_deadline_s
                      if deadline_s is None else float(deadline_s))
        deadline = time.monotonic() + deadline_s
        t_req0 = time.monotonic()
        t0_pc = time.perf_counter()
        # one causal chain per routed request: every try/hedge span and
        # every replica-side chain parents under this root
        trace_id = root_span = None
        if self._rec.enabled:
            trace_id, root_span = otrace.next_id(), otrace.next_id()
        with self._lock:
            self._counts["requests"] += 1
        budget = int(self.config.retry_budget)
        tried: set[str] = set()
        original: Optional[BaseException] = None
        retries = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                # exclude already-tried replicas first; once every
                # ROUTABLE replica has had a try, a remaining budget
                # may re-try anywhere (the transient may have cleared)
                # — counted against replicas _pick can actually route
                # to (active, or a probe-ready probation), not the
                # roster, or one dead/ejected replica would pin the
                # exclusion and surface errors with budget unspent
                exclude = (frozenset(tried)
                           if len(tried) < max(self._routable_count(), 1)
                           else frozenset())
                handle, probe = self._pick(exclude)
            except ServingRejected as exc:
                if original is None:
                    original = exc
                break
            tried.add(handle.name)
            try:
                out, service_s = self._try_one(
                    handle, probe, features, remaining,
                    trace_id, root_span,
                )
                with self._lock:
                    self._counts["ok"] += 1
                # retry + hedge + pick tax: the client's wall minus the
                # winning try's own service time (always-on attribution)
                _observe_overhead(
                    max(0.0, (time.monotonic() - t_req0) - service_s)
                )
                self._trace_root(trace_id, root_span, t0_pc, "ok",
                                 retries=retries)
                return out
            except (ServingRejected, ServingTimeout, ServingError) as exc:
                if original is None:
                    original = exc
                if not self._retryable(exc):
                    break
                if budget <= 0:
                    break
                budget -= 1
                retries += 1
                with self._lock:
                    self._counts["retries"] += 1
                _count_retry()
                continue
            except BaseException:
                # a non-serving failure (malformed request raising
                # before it enqueues) exits through here: close the
                # ledger — requests == ok + failed + client_errors
                # must always balance
                with self._lock:
                    self._counts["client_errors"] += 1
                self._trace_root(trace_id, root_span, t0_pc,
                                 "client_error", retries=retries)
                raise
        with self._lock:
            self._counts["failed"] += 1
        if original is None:
            original = ServingTimeout(
                f"request deadline {deadline_s:.3f}s expired before any "
                "replica could be tried"
            )
        self._trace_root(trace_id, root_span, t0_pc, "failed",
                         retries=retries,
                         error=type(original).__name__)
        raise original

    # ``submit`` would hand back a PendingRequest pinned to ONE replica,
    # which defeats retries/hedging — the router's unit of work is the
    # whole routed request, so only the blocking form is offered.
    __call__ = infer

    def _routable_count(self) -> int:
        """Replicas `_pick` could route to right now: active ones plus
        probation replicas whose probe window is open."""
        now = time.monotonic()
        n = 0
        with self._lock:
            for h in self.replicas:
                if h.dead:
                    continue
                st = self._state[h.name]
                if st["state"] == ACTIVE:
                    n += 1
                elif (not st["probe_inflight"]
                      and now - st["ejected_at"]
                      >= self.config.probation_s):
                    n += 1
        return n

    @staticmethod
    def _retryable(exc: BaseException) -> bool:
        if isinstance(exc, ServingRejected):
            return exc.reason in _RETRYABLE_REJECTS
        # ServingError: idempotent dispatch failure -> another replica
        # may be healthy.  ServingTimeout: the per-try cap fired with
        # client deadline left, or the client deadline itself expired —
        # the remaining-time check in the loop settles which.
        return isinstance(exc, (ServingError, ServingTimeout))

    def _try_one(self, handle, probe: bool, features, remaining: float,
                 trace_id: Optional[int] = None,
                 root_span: Optional[int] = None):
        """One routed try against `handle`, with the optional hedge.
        Returns ``(result, service_s)`` — the winning dispatch's own
        wall, for the router-overhead attribution — or raises; ALWAYS
        records the try's outcome on the replica's routing state.  With
        tracing on, the try (and hedge) each get a span under the
        request root, and the replica-side chain parents under it."""
        cap = remaining
        # a timeout only counts as a WEDGE strike when the router's own
        # per-try cap was the binding constraint — a client deadline
        # expiring says nothing about the replica's health
        wedge = (self.config.try_timeout_s is not None
                 and self.config.try_timeout_s < remaining)
        if self.config.try_timeout_s is not None:
            cap = min(cap, self.config.try_timeout_s)
        t_try0 = time.monotonic()
        # ids allocated BEFORE the submit: the replica-side spans must
        # be able to parent under the try while it is still in flight
        tinfo = None
        if trace_id is not None and self._rec.enabled:
            tinfo = _TryTrace(trace_id, otrace.next_id(), root_span,
                              "router.try", handle.name,
                              time.perf_counter())
        try:
            req = handle.submit(features, deadline_s=cap,
                                trace_ctx=tinfo.ctx if tinfo else None)
        except ServingRejected as exc:
            self._record(
                handle, "dead" if exc.reason == "replica_dead"
                else "rejected", probe,
            )
            self._trace_try(tinfo, "rejected", reason=exc.reason)
            raise
        except BaseException:
            # a NON-serving failure (e.g. wrong input arity raising
            # ValueError before the request ever enqueues) is a client
            # error, not a replica outcome: leave the routing state
            # untouched but RELEASE the probe slot, or a probation
            # replica whose probe drew a malformed request could never
            # be probed again
            if probe:
                self._release_probe(handle)
            self._trace_try(tinfo, "client_error")
            raise
        hedge_after = self.config.hedge_after_s
        if (hedge_after is None or hedge_after >= cap
                or len(self.replicas) < 2):
            return (self._resolve(handle, probe, req, cap, wedge, tinfo),
                    time.monotonic() - t_try0)
        if req._event.wait(min(hedge_after, cap)):
            return (self._resolve(handle, probe, req, 0.0, wedge, tinfo),
                    time.monotonic() - t_try0)
        # latency tail: ONE duplicate on a different replica
        try:
            alt, alt_probe = self._pick(frozenset((handle.name,)))
        except ServingRejected:
            return (self._resolve(handle, probe, req, cap, wedge, tinfo),
                    time.monotonic() - t_try0)
        t_left = cap - min(hedge_after, cap)
        hinfo = None
        if tinfo is not None:
            hinfo = _TryTrace(trace_id, otrace.next_id(), root_span,
                              "router.hedge", alt.name,
                              time.perf_counter())
        t_hedge0 = time.monotonic()
        try:
            hreq = alt.submit(features, deadline_s=max(t_left, 0.001),
                              trace_ctx=hinfo.ctx if hinfo else None)
        except ServingRejected as exc:
            self._record(alt, "rejected", alt_probe)
            self._trace_try(hinfo, "rejected", reason=exc.reason)
            return (self._resolve(handle, probe, req, cap, wedge, tinfo),
                    time.monotonic() - t_try0)
        with self._lock:
            self._counts["hedges"] += 1
        _count_hedge()
        end = time.monotonic() + t_left
        while time.monotonic() < end:
            if req.done:
                winner, wprobe, loser, lprobe = handle, probe, alt, alt_probe
                wreq, lreq = req, hreq
                break
            if hreq.done:
                winner, wprobe, loser, lprobe = alt, alt_probe, handle, probe
                wreq, lreq = hreq, req
                break
            req._event.wait(0.001)
        else:
            winner, wprobe, loser, lprobe = handle, probe, alt, alt_probe
            wreq, lreq = req, hreq
        winfo, linfo = (tinfo, hinfo) if wreq is req else (hinfo, tinfo)
        w_t0 = t_try0 if wreq is req else t_hedge0
        l_t0 = t_hedge0 if wreq is req else t_try0
        try:
            out = self._resolve(winner, wprobe, wreq, 0.0, wedge, winfo)
        except (ServingRejected, ServingTimeout, ServingError):
            # the faster completion FAILED: the slower duplicate is the
            # request's remaining hope — await it for the time left.
            # Only the PRIMARY had the full per-try cap by now; the
            # hedge only got the residual window, so a timeout there
            # must not count as a wedge strike against it
            return (self._resolve(loser, lprobe, lreq,
                                  end - time.monotonic(),
                                  wedge and loser is handle, linfo),
                    time.monotonic() - l_t0)
        # dedup: the slower duplicate is DISCARDED — cancelled so the
        # losing replica counts it (timeout) and its ledger balances,
        # and its routing state is left untouched (it did nothing wrong)
        lreq.cancelled = True
        if lprobe:
            self._release_probe(loser)
        self._trace_try(linfo, "discarded")
        return out, time.monotonic() - w_t0

    def _release_probe(self, handle) -> None:
        """Free a probe slot whose try resolved without a recordable
        outcome (discarded hedge loser, malformed request)."""
        with self._lock:
            self._state[handle.name]["probe_inflight"] = False

    def _resolve(self, handle, probe: bool, req, timeout: float,
                 wedge: bool = False, tinfo=None):
        """Await one try's PendingRequest and record the outcome.
        `wedge` = the per-try cap (not the client deadline) bounds this
        wait, so a timeout indicts the replica."""
        try:
            out = req.result(timeout=max(timeout, 0.0))
        except ServingRejected as exc:
            self._record(handle, "rejected", probe)
            self._trace_try(tinfo, "rejected", reason=exc.reason)
            raise
        except ServingTimeout:
            # wedge detector: the per-try deadline fired — the replica
            # took the request and never answered.  A bare client
            # deadline expiring is recorded WITHOUT a failure strike.
            self._record(handle, "timeout" if wedge else "client_timeout",
                         probe)
            self._trace_try(tinfo, "timeout")
            raise
        except ServingError:
            self._record(handle, "error", probe)
            self._trace_try(tinfo, "error")
            raise
        self._record(handle, "ok", probe)
        self._trace_try(tinfo, "ok")
        return out

    # -- request-trace helpers ---------------------------------------------
    def _trace_root(self, trace_id: Optional[int],
                    root_span: Optional[int], t0_pc: float, outcome: str,
                    **args) -> None:
        if trace_id is None or not self._rec.enabled:
            return
        self._rec.add_complete(
            "router.request", t0_pc, time.perf_counter() - t0_pc,
            cat="request",
            **otrace.trace_args(trace_id, root_span),
            router=self.name, outcome=outcome, **args,
        )

    def _trace_try(self, tinfo: Optional["_TryTrace"], outcome: str,
                   **args) -> None:
        """Close one try/hedge span (no-op when the request is
        untraced).  Recorded ONCE, at the try's terminal outcome."""
        if tinfo is None or not self._rec.enabled:
            return
        self._rec.add_complete(
            tinfo.name, tinfo.t0_pc, time.perf_counter() - tinfo.t0_pc,
            cat="request",
            **otrace.trace_args(tinfo.trace_id, tinfo.span_id,
                                tinfo.parent),
            replica=tinfo.replica, outcome=outcome, **args,
        )


class _TryTrace:
    """Span bookkeeping for one routed try/hedge: ids allocated before
    the submit so the replica-side chain can parent under it."""

    __slots__ = ("trace_id", "span_id", "parent", "name", "replica",
                 "t0_pc")

    def __init__(self, trace_id: int, span_id: int, parent: Optional[int],
                 name: str, replica: str, t0_pc: float):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent = parent
        self.name = name
        self.replica = replica
        self.t0_pc = t0_pc

    @property
    def ctx(self) -> tuple:
        return (self.trace_id, self.span_id)


# -- telemetry helpers (never on the request's critical path) ---------------

_OVERHEAD_HIST = None


def _observe_overhead(secs: float) -> None:
    """Per successful routed request — the family is resolved once
    (like server.py's `_breakdown_families`): the front door's hot path
    must not pay a registry lock + lookup per request."""
    global _OVERHEAD_HIST
    try:
        if _OVERHEAD_HIST is None:
            from deeplearning4j_tpu.observe.metrics import registry

            _OVERHEAD_HIST = registry().histogram(
                "dl4jtpu_router_overhead_seconds"
            )
        _OVERHEAD_HIST.observe(secs)
    except Exception as e:
        log.debug("router overhead metric failed: %s", e)


def _count_try(router: str, replica: str, outcome: str) -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_router_requests_total").inc(
            router=router, replica=replica, outcome=outcome,
        )
    except Exception as e:
        log.debug("router try metric failed: %s", e)


def _count_retry() -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_router_retries_total").inc()
    except Exception as e:
        log.debug("router retry metric failed: %s", e)


def _count_hedge() -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_router_hedges_total").inc()
    except Exception as e:
        log.debug("router hedge metric failed: %s", e)


def _count_ejection(reason: str) -> None:
    try:
        from deeplearning4j_tpu.observe.metrics import registry

        registry().counter("dl4jtpu_replica_ejections_total").inc(
            reason=reason,
        )
    except Exception as e:
        log.debug("router ejection metric failed: %s", e)


# -- process-global router listing + pressure collector ---------------------

_ROUTERS_LOCK = threading.Lock()
_ROUTERS: "weakref.WeakSet[Router]" = weakref.WeakSet()
_COLLECTOR_INSTALLED = False
_PRESSURE_SEEN: set = set()
_ROUTER_SEQ = 0


def _next_router_name() -> str:
    global _ROUTER_SEQ
    with _ROUTERS_LOCK:
        _ROUTER_SEQ += 1
        return f"router{_ROUTER_SEQ}"


def _register_router(router: Router) -> None:
    global _COLLECTOR_INSTALLED
    with _ROUTERS_LOCK:
        _ROUTERS.add(router)
        need_install = not _COLLECTOR_INSTALLED
        _COLLECTOR_INSTALLED = True
    if need_install:
        try:
            from deeplearning4j_tpu.observe.metrics import registry

            registry().register_collector(_pressure_collector)
        except Exception as e:
            log.debug("router pressure collector install failed: %s", e)
            with _ROUTERS_LOCK:
                _COLLECTOR_INSTALLED = False


def active_routers() -> list:
    with _ROUTERS_LOCK:
        return list(_ROUTERS)


def _pressure_collector() -> None:
    """Registry pull collector: refresh the per-replica pressure gauge
    from every live router at scrape time (and drop series for replicas
    that no longer exist — a dead fleet must not freeze its last
    pressure on /metrics forever)."""
    from deeplearning4j_tpu.observe.metrics import registry

    gauge = registry().gauge("dl4jtpu_router_replica_pressure")
    live = {}
    for router in active_routers():
        for h in router.replicas:
            # replica names are only unique WITHIN a fleet: key (and
            # label) by router too, or two fleets' r0 series merge
            live[(router.name, h.name)] = h.pressure()
    with _ROUTERS_LOCK:
        for router_name, name in _PRESSURE_SEEN - set(live):
            gauge.remove(router=router_name, replica=name)
        _PRESSURE_SEEN.clear()
        _PRESSURE_SEEN.update(live)
    for (router_name, name), p in live.items():
        gauge.set(p, router=router_name, replica=name)
