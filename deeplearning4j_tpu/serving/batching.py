"""Shape quantization for the serving plane — a bounded compiled-program set.

A compiled inference program specializes on the full input shape, so a
server that dispatches whatever batch happens to coalesce compiles one
XLA program per distinct (batch size x sequence length) it ever sees —
the recompile tax PR 1 evicted from training would move into the
serving hot path, one stall per novel shape, forever.

Two quantizers bound the set:

- **batch axis**: a coalesced batch of n requests pads up to the next
  power of two (capped at `max_batch`), so the server compiles at most
  ``log2(max_batch) + 1`` programs per input signature.  Padding rows
  are zeros; the real rows are sliced back out of the output.
- **time axis** (rank >= 2 single-input features, e.g. (T, F)
  sequences): padded up to `flags.bucket_length`'s quantum — the SAME
  quantization the training feed uses, so a fine-tune-and-serve loop
  shares its compile cache between the two planes.  A features mask
  marks the real steps.

Both are pure host-side numpy; the padded batch is what crosses H2D.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.runtime import flags


def batch_bucket(n: int, max_batch: int) -> int:
    """Smallest power of two >= n, capped at max_batch (n <= max_batch)."""
    if n > max_batch:
        raise ValueError(f"batch of {n} exceeds max_batch={max_batch}")
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


def bucket_signature(features: tuple, quantum: int | None,
                     sequence_axis: bool) -> tuple:
    """The signature a request batches under: per-input (shape sans
    batch, dtype), with the time axis already bucketed when sequence
    padding is on — requests of length 37 and 52 share the 64-bucket
    program."""
    sig = []
    for a in features:
        shape = tuple(a.shape)
        if sequence_axis and len(shape) >= 2:
            shape = (flags.bucket_length(shape[0], quantum),) + shape[1:]
        sig.append((shape, str(a.dtype)))
    return tuple(sig)


def pad_sequence(a: np.ndarray, quantum: int | None):
    """Pad ONE example's leading (time) axis up to its bucket; returns
    (padded, mask) where mask is 1.0 on real steps.  Rank-1 inputs and
    already-bucketed lengths pass through (mask still returned so the
    batcher can mix exact and padded requests in one batch)."""
    t = a.shape[0]
    tb = flags.bucket_length(t, quantum)
    mask = np.zeros((tb,), np.float32)
    mask[:t] = 1.0
    if tb == t:
        return a, mask
    pad_width = [(0, tb - t)] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad_width), mask


def stack_batch(rows: list[tuple], n_inputs: int,
                bucket: int) -> list[np.ndarray]:
    """Stack per-request examples into per-input batch arrays, padded
    with zero rows up to `bucket`.  `rows[i]` is request i's per-input
    tuple; every row shares a signature (the admission queue grouped
    them), so plain stacking is safe."""
    cols = []
    for j in range(n_inputs):
        col = np.stack([r[j] for r in rows])
        if bucket > len(rows):
            pad = np.zeros((bucket - len(rows),) + col.shape[1:], col.dtype)
            col = np.concatenate([col, pad])
        cols.append(col)
    return cols
