"""Speculative-decoding draft sources for the generation engine.

Plain continuous-batching decode advances every stream ONE token per
model dispatch — the memory-bandwidth-bound regime speculative decoding
attacks: a cheap DRAFTER proposes ``k`` tokens per stream, the engine
scores all of them (plus one bonus position) in a single chunked
"verify-once" forward over the paged KV cache
(`ops.paged_attention.paged_attention_chunk`), and rejection sampling
keeps the output distribution exactly the baseline's.

This module owns the draft side of that split: `DraftSource` is the
pluggable contract (``draft(history, k) -> up to k proposed tokens``),
with two implementations —

- `NGramDrafter` (default, ``"ngram"``) — self-drafting prompt-lookup:
  the longest n-gram suffix of the stream's history (prompt + generated
  tokens) is matched against its most recent earlier occurrence and the
  tokens that followed it are proposed.  Zero model cost, zero state,
  pure host numpy; it shines exactly where real decoding does — copy
  runs, repeated entities, structured output — and greedy decode's
  tendency to settle into repeating patterns makes it the honest
  default for the committed CPU bench.
- `ModelDrafter` (``"model"``) — the two-model configuration: a small
  zoo model decodes ``k`` tokens greedily (one bucketed forward per
  draft token, compiled once per `flags.bucket_length` bucket, so the
  drafter's compiled-program set is bounded the same way the engine's
  is).  Greedy drafting is deterministic, which the engine's
  rejection-sampling parity contract relies on.

Drafts are PROPOSALS, never outputs: the engine samples the target
model's token at every chunk position with the baseline ``fold_in`` key
schedule and emits the accepted prefix plus that sample — a drafter
returning garbage (see the ``serving.draft`` fault site's ``corrupt``
kind) costs acceptance, never correctness.

Knobs (read by `GenerationConfig` resolution, overridable per request):
``DL4J_TPU_SPEC_K`` (draft length; 0 disables) and
``DL4J_TPU_SPEC_DRAFTER`` (``ngram`` | ``model``).
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.generation import (
    _block_prefill,
    _head_logits,
    _plan,
)
from deeplearning4j_tpu.runtime.flags import bucket_length

log = logging.getLogger("deeplearning4j_tpu")

ENV_SPEC_K = "DL4J_TPU_SPEC_K"
ENV_SPEC_DRAFTER = "DL4J_TPU_SPEC_DRAFTER"

DRAFTER_NAMES = ("ngram", "model")

_EMPTY = np.zeros(0, np.int32)


class DraftSource:
    """The pluggable drafter contract.

    ``draft(history, k)`` returns UP TO ``k`` proposed continuation
    tokens (int32, possibly empty) for a stream whose full token
    history (prompt + everything generated so far, including the token
    the next step will process) is ``history``.  Must be deterministic
    for a given history — the engine's byte-parity contract samples the
    target model at every position regardless, but a deterministic
    drafter keeps acceptance measurements reproducible.  Called from
    the engine thread BETWEEN dispatches; implementations must not
    block on anything slower than a small host computation or a single
    bounded device call.
    """

    name = "none"

    def draft(self, history: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class NGramDrafter(DraftSource):
    """Self-drafting prompt-lookup (assisted-generation style): find
    an earlier occurrence of the longest n-gram suffix of the history
    and propose the tokens that followed it — preferring the most
    recent occurrence that still has a full k-token continuation."""

    name = "ngram"

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if min_n < 1 or max_n < min_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got "
                             f"[{min_n}, {max_n}]")
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def draft(self, history: np.ndarray, k: int) -> np.ndarray:
        h = np.asarray(history, np.int32).reshape(-1)
        n_hist = h.shape[0]
        if k <= 0 or n_hist < 2:
            return _EMPTY
        for n in range(min(self.max_n, n_hist - 1), self.min_n - 1, -1):
            suffix = h[n_hist - n:]
            # windows over h[:-1]: the suffix's own occurrence is
            # excluded, every earlier one is a candidate
            win = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
            hits = np.nonzero((win == suffix).all(axis=1))[0]
            if hits.size:
                # most recent occurrence with a FULL k-token
                # continuation; an occurrence butting against the end
                # of history would propose a truncated draft (cyclic
                # tails hit this every step)
                full = hits[hits + n + k <= n_hist]
                i = int(full[-1] if full.size else hits[-1])
                return h[i + n: i + n + k].copy()
        return _EMPTY


class ModelDrafter(DraftSource):
    """Two-model drafting: a small zoo model greedily decodes ``k``
    tokens from the history.  One bucketed full forward per draft token
    — no KV cache of its own — compiled once per bucket, so a serving
    life adds a bounded handful of drafter programs."""

    name = "model"

    def __init__(self, model, quantum: int = 16):
        if model.params is None:
            model.init()
        self.model = model
        self._quantum = int(quantum)
        self._fns: dict = {}
        embed, pos, blocks, head = _plan(model)
        self._stack = (embed, pos, tuple(blocks), head)
        names = [l.name for l in model.conf.layers]
        self._embed_name, self._head_name = names[0], names[-1]
        self._pos_name = pos.name if pos is not None else None
        self._block_names = [b.name for b in blocks]

    def _fn(self, t_b: int):
        fn = self._fns.get(t_b)
        if fn is not None:
            return fn
        embed, pos, blocks, head = self._stack
        pos_name, head_name = self._pos_name, self._head_name
        block_names, embed_name = self._block_names, self._embed_name
        dt = jnp.bfloat16 if self.model._bf16 else jnp.float32

        @jax.jit
        def last_greedy(params, toks_pad, true_len):
            E = params[embed_name]["W"].astype(dt)
            x = embed._act()(E[toks_pad])
            if pos is not None:
                x, _ = pos.apply(params.get(pos_name, {}), {}, x)
            for cfg_b, nm in zip(blocks, block_names):
                x, _, _ = _block_prefill(cfg_b, params[nm], x, None)
            h_last = x[0, true_len - 1]
            logits = _head_logits(head, params[head_name], h_last)
            return jnp.argmax(logits).astype(jnp.int32)

        self._fns[t_b] = last_greedy
        return last_greedy

    def draft(self, history: np.ndarray, k: int) -> np.ndarray:
        toks = np.asarray(history, np.int32).reshape(-1)
        if k <= 0 or toks.shape[0] < 1:
            return _EMPTY
        _, pos, _, _ = self._stack
        if (pos is not None and pos.learned
                and toks.shape[0] + k > pos.max_length):
            return _EMPTY                 # would overflow the draft PE
        out = []
        for _ in range(k):
            n = toks.shape[0]
            t_b = bucket_length(n, self._quantum)
            pad = np.zeros((1, t_b), np.int32)
            pad[0, :n] = toks
            nxt = int(self._fn(t_b)(self.model.params, pad, np.int32(n)))
            out.append(nxt)
            toks = np.append(toks, np.int32(nxt))
        return np.asarray(out, np.int32)


def make_drafter(name: str, *, draft_model=None) -> DraftSource:
    """Resolve a drafter by knob value (`DL4J_TPU_SPEC_DRAFTER` /
    `GenerationConfig.spec_drafter`)."""
    name = (name or "ngram").strip().lower()
    if name in ("ngram", "prompt_lookup", "lookup"):
        return NGramDrafter()
    if name == "model":
        if draft_model is None:
            raise ValueError(
                "drafter 'model' needs a draft model "
                "(GenerationConfig.spec_draft_model)"
            )
        return ModelDrafter(draft_model)
    raise ValueError(
        f"unknown drafter {name!r} (one of {DRAFTER_NAMES})"
    )


def spec_k_from_env(default: int = 0) -> int:
    """`DL4J_TPU_SPEC_K` as an int (0 = speculative decode off)."""
    raw = os.environ.get(ENV_SPEC_K, "").strip()
    if not raw:
        return default
    try:
        k = int(raw)
    except ValueError:
        log.warning("bad %s=%r (want an int); speculative decode off",
                    ENV_SPEC_K, raw)
        return default
    return max(0, k)


def drafter_from_env(default: str = "ngram") -> str:
    return os.environ.get(ENV_SPEC_DRAFTER, "").strip().lower() or default
